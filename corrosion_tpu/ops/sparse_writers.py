"""Sparse writer axis: rotating hot slots + per-node deviation tables.

Any of N nodes may write, but the dense ``[N, W]`` version-vector tensors
of ops/gossip.py make writer columns a scarce resource: at 100k nodes a
dense any-node-writes plane would need 40 GB for one u32 table. The
reference keeps per-actor bookkeeping in hash maps, naturally sparse
(corro-types/src/agent.rs:945-1052), and writes originate anywhere
(doc/crdts.md:25-28). The TPU-shaped equivalent exploits TEMPORAL
sparsity: at any moment only writers with *recent* activity have
cluster-visible lag; a quiescent writer's stream is fully replicated
everywhere, so its row of every node's version vector compresses to "==
head".

Design:

- ``w_hot`` rotating SLOTS carry the dense plane for currently-active
  writers. Every gossip kernel runs unchanged over the slot axis; queue
  entries additionally carry the writer's GLOBAL id
  (GossipConfig.track_writer_ids) so CRDT cell derivation keys on
  identity and slot reuse across epochs can never collide cell keys.
- COLD writers (demoted slots) satisfy the invariant "every node holds
  versions 1..head_full[w]" EXCEPT where a bounded per-node deviation
  table records (writer, contig) lag.
- Demotion is gated, two ways:
  * zero-lag: a quiescent slot whose stream every node has fully applied
    demotes for free (no deviation entries anywhere) — the common case;
  * forced: under slot pressure a quiescent slot may demote while
    laggards remain, inserting deviation entries — but only while every
    node's table has headroom (``demote_report`` proves it first).
    A deviation entry is NEVER silently dropped: dropping one would
    over-claim possession (the node would assert versions it does not
    hold). The failure mode under extreme pressure is backpressure on
    slot turnover — never forgotten lag.
- ``cold_sync`` heals deviation entries by pulling from the stream's
  origin node (the canonical holder, like the reference's by-actor sync
  peer choice, agent.rs:2383-2423), budgeted per session, CRDT cells
  merged for every granted version.

Rotation happens at EPOCH boundaries between scan chunks (the engine
already chunks device executions), host-planned and device-checked.
Out-of-order window bits above a demoted slot's contig are dropped
(possession under-claim — always safe; sync re-grants the content).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from corrosion_tpu.ops import crdt, onehot
from corrosion_tpu.ops.gossip import (
    DataState,
    GossipConfig,
    _merge_versions_dense,
)


@dataclass(frozen=True)
class SparseConfig:
    """Knobs for the rotating-slot writer plane."""

    epoch_rounds: int = 16  # rotation cadence (aligned with scan chunks)
    k_dev: int = 64  # deviation-table capacity per node
    d_max: int = 256  # max slot retirements per epoch (static pad)
    p_max: int = 256  # max promotions per epoch (static pad)
    demote_after: int = 1  # quiescent epochs before a slot may demote
    cold_budget: int = 64  # versions healed per node per cold_sync session
    cold_chunk: int = 32  # versions per deviation entry per session


class SparseState(NamedTuple):
    data: DataState  # the hot plane ([N, w_hot] slot tensors)
    head_full: jax.Array  # u32[N] committed head per NODE (global writers)
    slot_writer: jax.Array  # i32[w_hot] node id per slot, -1 empty
    dev_writer: jax.Array  # i32[N, k_dev] global writer id, -1 empty
    dev_contig: jax.Array  # u32[N, k_dev] lagging watermark
    dev_any: jax.Array  # bool[] any deviation entry exists (lax.cond gate)


def init_sparse(cfg: GossipConfig, sp: SparseConfig) -> SparseState:
    from corrosion_tpu.ops.gossip import init_data

    n = cfg.n_nodes
    return SparseState(
        data=init_data(cfg),
        head_full=jnp.zeros((n,), jnp.uint32),
        slot_writer=jnp.full((cfg.n_writers,), -1, jnp.int32),
        dev_writer=jnp.full((n, sp.k_dev), -1, jnp.int32),
        dev_contig=jnp.zeros((n, sp.k_dev), jnp.uint32),
        dev_any=jnp.array(False, dtype=bool),
    )


def _col_gather(table: jax.Array, slots: jax.Array) -> jax.Array:
    """[N, D] = table[:, slots] for SHARED column indices: one exact
    one-hot matmul (u16 halves ride the MXU; all of u32 exact at HIGHEST
    precision). A per-row block gather here materialized [N, D, 128] —
    59 GB at the 100k rotation shapes — and a strided column gather
    serializes."""
    w = table.shape[1]
    sel = (
        slots[:, None] == jnp.arange(w, dtype=slots.dtype)[None, :]
    ).astype(jnp.float32)  # [D, W]

    def dot(x):
        return jnp.einsum(
            "nw,dw->nd", x, sel, precision=jax.lax.Precision.HIGHEST
        )

    return onehot.exact_u32_apply(dot, table)


@partial(jax.jit, static_argnames=())
def demote_report(
    state: SparseState,
    cand_slots: jax.Array,  # i32[D] candidate slots (clipped, padded)
    cand_ok: jax.Array,  # bool[D]
) -> tuple[jax.Array, jax.Array]:
    """Device-side feasibility for a host-proposed retirement list.

    Returns (caught_up[D], maxload[D]):
    - caught_up[d]: every node's hot contig equals the slot head (zero-lag
      demotion is free);
    - maxload[d]: max over nodes of (deviation-table occupancy + new
      entries if candidates 0..d were ALL force-demoted) — the host
      force-demotes the longest prefix with maxload <= k_dev.
    """
    data = state.data
    cs = jnp.maximum(cand_slots, 0)
    contig_c = _col_gather(data.contig, cs)  # u32[N, D]
    head_c = data.head[cs]  # [D] (tiny gather)
    lag = (head_c[None, :] - contig_c).astype(jnp.uint32) * cand_ok[None, :]
    caught_up = jnp.sum(lag > 0, axis=0, dtype=jnp.int32) == 0
    occ = jnp.sum(state.dev_writer >= 0, axis=1, dtype=jnp.int32)  # [N]
    adds = jnp.cumsum((lag > 0).astype(jnp.int32), axis=1)  # [N, D]
    maxload = jnp.max(occ[:, None] + adds, axis=0)  # [D]
    return caught_up, maxload


@partial(jax.jit, static_argnames=("cfg",))
def rotate(
    state: SparseState,
    retire_slots: jax.Array,  # i32[D] slots to retire (padded)
    retire_ok: jax.Array,  # bool[D]
    promote_slots: jax.Array,  # i32[P] target slots (padded)
    promote_writers: jax.Array,  # i32[P] node ids taking the slots
    promote_ok: jax.Array,  # bool[P]
    cfg: GossipConfig,
) -> tuple[SparseState, dict]:
    """Epoch transition: retire slots (inserting deviation entries for
    laggards), then promote new writers into free slots (consuming any
    deviation entries for them). The host guarantees feasibility via
    demote_report; ``dev_dropped`` in the returned stats must stay 0 (a
    nonzero value means an over-claim and is asserted on by the engine).
    """
    from corrosion_tpu.ops import routing

    data = state.data
    n, w_hot = cfg.n_nodes, cfg.n_writers
    d = retire_slots.shape[0]
    p = promote_slots.shape[0]
    rs = jnp.maximum(retire_slots, 0)
    ps = jnp.maximum(promote_slots, 0)

    # ---- retire: write heads back, insert deviation entries ----------------
    writer_ret = jnp.where(
        retire_ok, state.slot_writer[rs], -1
    )  # i32[D] global ids
    head_ret = data.head[rs]  # u32[D]
    head_full = state.head_full.at[
        jnp.where(retire_ok & (writer_ret >= 0), writer_ret, n)
    ].set(head_ret, mode="drop")

    contig_ret = _col_gather(data.contig, rs)  # u32[N, D]
    lag_mask = (
        (contig_ret < head_ret[None, :])
        & retire_ok[None, :]
        & (writer_ret[None, :] >= 0)
    )
    cand_w = jnp.concatenate(
        [
            state.dev_writer,
            jnp.where(lag_mask, writer_ret[None, :], -1),
        ],
        axis=1,
    )
    cand_c = jnp.concatenate([state.dev_contig, contig_ret], axis=1)
    cand_valid = cand_w >= 0
    keep, (dev_writer, dev_contig) = routing.rebuild_bounded_queue(
        cand_valid, cand_valid.astype(jnp.int32), (cand_w, cand_c),
        state.dev_writer.shape[1],
    )
    dev_writer = jnp.where(keep, dev_writer, -1)
    dev_dropped = jnp.sum(cand_valid, dtype=jnp.int32) - jnp.sum(
        keep, dtype=jnp.int32
    )

    retired_col = (
        jnp.zeros((w_hot,), bool)
        .at[jnp.where(retire_ok, rs, w_hot)]
        .set(True, mode="drop")
    )
    slot_writer = jnp.where(retired_col, -1, state.slot_writer)

    # ---- promote: init columns from head_full, refined by dev entries ------
    pw = jnp.maximum(promote_writers, 0)
    # head_full AFTER the retire writeback (a writer promoted this epoch
    # cannot also be retiring this epoch — host invariant — so this only
    # matters for writers retired in earlier epochs).
    claim_default = jnp.broadcast_to(head_full[pw][None, :], (n, p))

    # Writer-id -> promotion index lookup table (P is a sentinel).
    promo_idx = (
        jnp.full((n + 1,), p, jnp.int32)
        .at[jnp.where(promote_ok, pw, n)]
        .set(jnp.arange(p, dtype=jnp.int32), mode="drop")
    )

    def _refine(args):
        claims, dev_w, dev_c = args
        # Per deviation entry: is its writer being promoted this epoch?
        # Flat [N, K] gathers/scatters serialize on TPU but run at epoch
        # cadence and only while entries exist (this cond); the dense
        # [N, K, P] compare would materialize gigabytes at 100k.
        k_dev = dev_w.shape[1]
        idx = promo_idx[jnp.maximum(dev_w, 0)]  # [N, K]
        hit = (idx < p) & (dev_w >= 0)
        # A node has at most one entry per writer, so a plain scatter of
        # entry claims into the [N, P] claim matrix is collision-free.
        rowi = jnp.broadcast_to(jnp.arange(n)[:, None], (n, k_dev))
        pos = jnp.where(hit, rowi * p + idx, n * p)
        claims = (
            claims.reshape(-1)
            .at[pos.reshape(-1)]
            .set(dev_c.reshape(-1), mode="drop")
            .reshape(n, p)
        )
        dev_w = jnp.where(hit, -1, dev_w)
        return claims, dev_w, dev_c

    claims, dev_writer, dev_contig = jax.lax.cond(
        state.dev_any,
        _refine,
        lambda args: args,
        (claim_default, dev_writer, dev_contig),
    )

    promoted_col = (
        jnp.zeros((w_hot,), bool)
        .at[jnp.where(promote_ok, ps, w_hot)]
        .set(True, mode="drop")
    )
    # Scatter claims into the promoted columns with an exact one-hot
    # matmul (u16 halves; a [N, P]→[N, W] column scatter serializes).
    sel = (
        ps[:, None] == jnp.arange(w_hot)[None, :]
    ).astype(jnp.float32) * promote_ok[:, None].astype(jnp.float32)  # [P, W]

    def _cols(vals):  # u32[N, P] -> u32[N, W] (zeros off promoted cols)
        def dot(x):
            return jnp.einsum(
                "np,pw->nw", x, sel,
                precision=jax.lax.Precision.HIGHEST,
            )

        return onehot.exact_u32_apply(dot, vals)

    claim_cols = _cols(claims)
    contig = jnp.where(
        promoted_col[None, :],
        claim_cols,
        jnp.where(retired_col[None, :], 0, data.contig),
    )
    seen = jnp.where(
        promoted_col[None, :],
        claim_cols,
        jnp.where(retired_col[None, :], 0, data.seen),
    )
    # Window bits for retired/promoted columns drop (possession
    # under-claim — safe; content re-granted by sync if ever needed).
    col_reset = retired_col | promoted_col
    oo = jnp.where(col_reset[None, None, :], jnp.uint32(0), data.oo)
    head = jnp.where(
        promoted_col,
        (
            jnp.zeros((w_hot,), jnp.uint32)
            .at[jnp.where(promote_ok, ps, w_hot)]
            .set(head_full[pw], mode="drop")
        ),
        jnp.where(retired_col, 0, data.head),
    )
    slot_writer = jnp.where(
        promoted_col,
        (
            jnp.full((w_hot,), -1, jnp.int32)
            .at[jnp.where(promote_ok, ps, w_hot)]
            .set(promote_writers, mode="drop")
        ),
        slot_writer,
    )

    # Queue entries referencing reset slots die (their content is already
    # applied at its holders; receivers that never got it lag on the
    # retired writer and heal through deviations/cold_sync). q_writer
    # holds slot ids; map through the [W] reset mask with the shared-table
    # block gather (a direct [N, Q, D+P] compare materializes gigabytes).
    q_dead = onehot.table_gather_u32(
        col_reset.astype(jnp.uint32), jnp.maximum(data.q_writer, 0)
    )
    q_writer = jnp.where(
        (q_dead > 0) & (data.q_writer >= 0), -1, data.q_writer
    )

    dev_any = jnp.any(dev_writer >= 0)
    stats = {
        "retired": jnp.sum(retire_ok & (writer_ret >= 0), dtype=jnp.int32),
        "promoted": jnp.sum(promote_ok, dtype=jnp.int32),
        "dev_entries": jnp.sum(dev_writer >= 0, dtype=jnp.int32),
        "dev_dropped": dev_dropped,
    }
    return (
        SparseState(
            data=data._replace(
                contig=contig,
                seen=seen,
                oo=oo,
                oo_any=jnp.any(oo) if cfg.window_k else data.oo_any,
                head=head,
                q_writer=q_writer,
            ),
            head_full=head_full,
            slot_writer=slot_writer,
            dev_writer=dev_writer,
            dev_contig=dev_contig,
            dev_any=dev_any,
        ),
        stats,
    )


@partial(jax.jit, static_argnames=("cfg", "sp"))
def cold_sync(
    state: SparseState,
    region: jax.Array,  # i32[N] region per node
    alive: jax.Array,  # bool[N]
    partition: jax.Array,  # bool[R, R]
    cfg: GossipConfig,
    sp: SparseConfig,
) -> tuple[SparseState, dict]:
    """Heal deviation entries by pulling from each stream's origin node
    (the canonical holder — it committed the versions). Budgeted per node
    per session; granted versions merge their CRDT cells exactly like the
    hot sync grant replay. Gated on dev_any: epochs with empty tables pay
    one predicate."""

    def _go(state):
        n = cfg.n_nodes
        dev_w = state.dev_writer
        dev_c = state.dev_contig
        k_dev = dev_w.shape[1]
        wsafe = jnp.maximum(dev_w, 0)
        # Reachability of the origin: alive and not partitioned from us.
        # ([N, K] fancy gathers from 1-D tables — serialized on TPU, but
        # only paid while deviation entries exist.)
        alive_i = alive.astype(jnp.int32)[wsafe] > 0
        reg_w = region[wsafe]
        part_i = partition.astype(jnp.int32)
        ok = (
            (dev_w >= 0)
            & alive_i
            & (part_i[region[:, None], reg_w] == 0)
        )
        target = state.head_full[wsafe]  # u32[N, K]
        deficit = jnp.where(ok, target - jnp.minimum(target, dev_c), 0)
        per_e = jnp.minimum(deficit, jnp.uint32(sp.cold_chunk)).astype(
            jnp.int32
        )
        cum = jnp.cumsum(per_e, axis=1)
        grant = jnp.clip(
            jnp.int32(sp.cold_budget) - (cum - per_e), 0, per_e
        ).astype(jnp.uint32)
        new_c = dev_c + grant
        healed = jnp.sum(grant, dtype=jnp.uint32)

        cells = state.data.cells
        n_merges = jnp.uint32(0)
        if cfg.n_cells > 0:
            # Enumerate granted (writer, version) pairs into [N, B] and
            # merge their cells (the replay of peer.rs:610-666 for the
            # cold plane). k_dev is narrow: dense one-hot ops suffice.
            b = sp.cold_budget
            e = jnp.arange(b, dtype=jnp.int32)
            gcum = jnp.cumsum(grant.astype(jnp.int32), axis=1)
            e_idx = jnp.sum(
                gcum[:, None, :] <= e[None, :, None], axis=2,
                dtype=jnp.int32,
            )  # [N, B] entry owning unit e
            e_idx = jnp.minimum(e_idx, k_dev - 1)
            prev = jnp.where(
                e_idx > 0,
                onehot.rowgather(
                    gcum.astype(jnp.uint32), jnp.maximum(e_idx - 1, 0)
                ).astype(jnp.int32),
                0,
            )
            ver = (
                onehot.rowgather(dev_c, e_idx)
                + 1
                + (e[None, :] - prev).astype(jnp.uint32)
            )
            gw = onehot.rowgather(wsafe.astype(jnp.uint32), e_idx)
            mask = e[None, :] < gcum[:, -1][:, None]
            cells, n_merges = _merge_versions_dense(
                cells, None, gw, ver, mask, None, n, cfg
            )

        # Entries that reached the cold head clear.
        done = ok & (new_c >= target)
        dev_w2 = jnp.where(done, -1, dev_w)
        return (
            state._replace(
                data=state.data._replace(cells=cells),
                dev_writer=dev_w2,
                dev_contig=new_c,
                dev_any=jnp.any(dev_w2 >= 0),
            ),
            {"cold_healed": healed, "cold_merges": n_merges},
        )

    def _skip(state):
        return state, {
            "cold_healed": jnp.uint32(0),
            "cold_merges": jnp.uint32(0),
        }

    return jax.lax.cond(state.dev_any, _go, _skip, state)


def cold_visibility(
    state: SparseState,
    sample_writer: jax.Array,  # i32[S] global writer (node) ids
    sample_ver: jax.Array,  # u32[S]
) -> jax.Array:
    """bool[S, N] visibility of sampled writes against the COLD plane:
    a cold write is held everywhere except at nodes with a deviation
    entry below it. (Samples whose writer is currently hot are answered
    by gossip.visibility on the slot plane instead.)"""

    def _go(_):
        # Per-sample map bounds the [chunk, N, K] compare transient: the
        # flat [S, N, K] form materializes gigabytes at (256, 100k, 256).
        def one(args):
            w, v = args
            lag = (state.dev_writer == w) & (state.dev_contig < v)
            return ~jnp.any(lag, axis=1)  # [N]

        return jax.lax.map(
            one, (sample_writer, sample_ver), batch_size=16
        )

    return jax.lax.cond(
        state.dev_any,
        _go,
        lambda _: jnp.ones(
            (sample_writer.shape[0], state.dev_writer.shape[0]), bool
        ),
        None,
    )


def cold_need(state: SparseState) -> jax.Array:
    """Σ outstanding deviation lag (the cold component of total_need)."""
    target = state.head_full[jnp.maximum(state.dev_writer, 0)]
    lag = jnp.where(
        state.dev_writer >= 0,
        target - jnp.minimum(target, state.dev_contig),
        0,
    )
    return jnp.sum(lag, dtype=jnp.uint32)


# corro-lint: disable=CT001,CT002,CT004 reason=host ground-truth reference
def serial_merge_reference_sparse(
    head_full, cfg: GossipConfig
) -> crdt.CellState:
    """Ground truth for any-node-writes runs: merge every committed
    version (w = NODE id, v <= head_full[w]) into one fresh cell state."""
    import numpy as np

    head_full = np.asarray(head_full)
    state = crdt.make_cells(cfg.n_cells)
    ws, vs = [], []
    for w in np.nonzero(head_full)[0]:
        for v in range(1, int(head_full[w]) + 1):
            ws.append(w)
            vs.append(v)
    if not ws:
        return state
    ws = jnp.asarray(np.array(ws, np.uint32))
    vs = jnp.asarray(np.array(vs, np.uint32))
    mask = jnp.ones(ws.shape, bool)
    for j in range(cfg.cells_per_write):
        key, cl, cv, vr = crdt.derive_change(
            ws, vs, jnp.uint32(j), cfg.n_cells
        )
        state = crdt.apply_changes(
            state,
            crdt.ChangeBatch(
                key=key, cl=cl, col_version=cv, value_rank=vr, mask=mask
            ),
        )
    return state
