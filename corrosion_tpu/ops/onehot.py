"""One-hot row reductions: the data plane's scatter/gather replacement.

Two primitives used throughout the gossip kernels (see ops/gossip.py):

- ``rowmax(idx, val, mask, width)``:  out[r, x] = max over m with
  idx[r, m] == x of val[r, m]   (a row-local scatter-max)
- ``rowgather(table, idx)``:          out[r, m] = table[r, idx[r, m]]
  (a row-local take_along_axis)

Why not scatter/gather? TPU scatters serialize per element (~70M elem/s
measured on v5e — 207 ms for a [100k, 144] scatter into [100k, 512]) and
dynamic gathers lower similarly badly (269 ms). Why not a plain jnp
one-hot broadcast? In context XLA materializes the [R, M, W] compare /
select intermediates to HBM when they have multiple consumers — measured
331 GB of HBM traffic per broadcast round at 100k nodes, ~0.5 s of pure
bandwidth.

The Pallas kernels below block rows into VMEM tiles and loop over the
small axis, so the [tile, W] accumulator lives in registers/VMEM and HBM
traffic is exactly inputs + outputs (a few hundred MB per round). The jnp
fallback (small shapes, non-TPU accelerator backends) is the same math.

On **CPU** the trade inverts completely: XLA:CPU lowers scatter/gather to
tight serial loops (no per-element device round-trip), while the dense
one-hot broadcast does O(R·M·W) compare+select lanes of real work.
Measured at the 512-node bench shapes: ``rowmax`` 318 ms dense vs 9.5 ms
native scatter-max, ``rowgather`` 305 ms dense vs 0.9 ms
``take_along_axis`` — the whole r05 CPU-fallback bench regression in two
primitives. Every primitive below therefore dispatches on backend at
trace time: native scatter/gather on CPU, one-hot/MXU forms elsewhere.
Results are bit-identical either way (all-integer max/add/select), which
``tests/test_perf_plane.py`` pins by running both paths.

Reference anchor: these implement the batched merge/delivery promotions of
corro-agent's broadcast plane (broadcast/mod.rs:356-567) and the CRDT
scatter-merge (crsql `INSERT INTO crsql_changes` replay, agent.rs:2192-2214)
at simulator scale.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Rows per grid program (amortizes DMA latency) and per inner sub-tile
# (bounds the [sub, M, W] register/VMEM temporary to ~2.4 MB at M=144,
# W=512).
_BLOCK_ROWS = 256
_SUB_ROWS = 8
# Below this many one-hot lanes (rows·M·width) the jnp broadcast form stays
# in cache/fusion range and beats a kernel launch.
_PALLAS_MIN_LANES = 1 << 27


def _block_rows(m: int, width: int) -> int:
    return _BLOCK_ROWS


def _use_pallas(lanes: int) -> bool:
    # Off by default: measured on v5e at wan_100k shapes, the fused jnp
    # broadcast form beat these kernels (567 vs 651 ms broadcast plane) —
    # XLA's materialized one-hot intermediates still stream at near-HBM
    # bandwidth while the VMEM-tiled kernels are VPU-throughput-bound.
    # CORRO_ONEHOT_PALLAS=1 re-enables for experiments.
    import os

    if os.environ.get("CORRO_ONEHOT_PALLAS", "0") != "1":
        return False
    return jax.default_backend() == "tpu" and lanes >= _PALLAS_MIN_LANES


# Backend dispatch for the native scatter/gather forms. None = auto
# (native on CPU, dense one-hot elsewhere); tests force either path via
# the module global (the _FAST_MAX_WRITERS override convention) — flip it
# BEFORE tracing, or clear_cache() the jitted callers, since the choice
# is baked in at trace time.
_NATIVE_SCATTER: bool | None = None


def _use_native() -> bool:
    if _NATIVE_SCATTER is not None:
        return _NATIVE_SCATTER
    return jax.default_backend() == "cpu"


def _pad_rows(x: jax.Array, rows_p: int):
    r = x.shape[0]
    if rows_p == r:
        return x
    pad = [(0, rows_p - r)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, pad)


# -- rowmax -------------------------------------------------------------------


def _flip(u32val: jax.Array) -> jax.Array:
    """u32 → i32 preserving order (Mosaic can't reduce unsigned ints);
    u32 0 maps to i32 min, so the 'no entry' floor survives the trip."""
    return (u32val ^ jnp.uint32(1 << 31)).astype(jnp.int32)


def _unflip(i32val: jax.Array) -> jax.Array:
    return i32val.astype(jnp.uint32) ^ jnp.uint32(1 << 31)


def _rowmax_kernel(idx_ref, val_ref, out_ref):
    # Big row blocks amortize per-program DMA latency; the inner loop
    # walks 8-row sub-tiles (sublane-aligned dynamic slices are legal)
    # whose [8, M, W] one-hot temporaries live in registers/VMEM. Nothing
    # reaches HBM but the inputs and the [bn, W] result.
    bn, m = idx_ref.shape
    w = out_ref.shape[1]
    ids = jax.lax.broadcasted_iota(jnp.int32, (_SUB_ROWS, m, w), 2)

    def body(t, _):
        r0 = t * _SUB_ROWS
        hit = idx_ref[pl.ds(r0, _SUB_ROWS), :][:, :, None] == ids
        vi = _flip(val_ref[pl.ds(r0, _SUB_ROWS), :])[:, :, None]
        out_ref[pl.ds(r0, _SUB_ROWS), :] = _unflip(
            jnp.max(jnp.where(hit, vi, jnp.int32(-(2**31))), axis=1)
        )
        return 0

    jax.lax.fori_loop(0, bn // _SUB_ROWS, body, 0)


def rowmax(
    idx: jax.Array,  # i32[R, M] column index per entry (any value ok if masked)
    val: jax.Array,  # u32[R, M]
    mask: jax.Array | None,  # bool[R, M] live entries (None = all)
    width: int,
) -> jax.Array:
    """out[r, x] = max over masked m with idx[r, m] == x of val[r, m], 0
    when none. Masked/out-of-range entries contribute nothing."""
    r, m = idx.shape
    val = val.astype(jnp.uint32)
    if mask is not None:
        idx = jnp.where(mask, idx, -1)
        val = jnp.where(mask, val, 0)
    if _use_native():
        # Native row-local scatter-max. Out-of-range/masked entries route
        # to a dropped sentinel column (scatter mode="drop" ignores them
        # — same contribution as the dense form's missed compare).
        rows = jnp.arange(r, dtype=jnp.int32)[:, None]
        safe = jnp.where((idx >= 0) & (idx < width), idx, width)
        return (
            jnp.zeros((r, width), jnp.uint32)
            .at[rows, safe]
            .max(val, mode="drop")
        )
    if not _use_pallas(r * m * width):
        # Reduce over the MINOR-MOST axis: [R, W, M] with the M messages
        # last lets XLA fuse the compare+select straight into a row
        # reduction (the [R, M, W] middle-axis form materialized ~30 GB
        # per call at wan_100k shapes).
        ids = jnp.arange(width, dtype=idx.dtype)
        hit = idx[:, None, :] == ids[None, :, None]
        return jnp.max(jnp.where(hit, val[:, None, :], 0), axis=2)
    bn = _block_rows(m, width)
    rows_p = -(-r // bn) * bn
    out = pl.pallas_call(
        _rowmax_kernel,
        out_shape=jax.ShapeDtypeStruct((rows_p, width), jnp.uint32),
        grid=(rows_p // bn,),
        in_specs=[
            pl.BlockSpec((bn, m), lambda i: (i, 0)),
            pl.BlockSpec((bn, m), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, width), lambda i: (i, 0)),
    )(_pad_rows(idx.astype(jnp.int32), rows_p), _pad_rows(val, rows_p))
    return out[:r]


# -- rowgather_wide -----------------------------------------------------------


def rowgather_wide(table: jax.Array, idx: jax.Array, blk: int = 128) -> jax.Array:
    """out[r, m] = table[r, idx[r, m]] for WIDE tables (thousands of
    columns), where both the dense one-hot form (O(R·M·W) lanes) and
    take_along_axis (serialized per-element gather, ~17 ms per 1.4M
    elements on v5e) are losing propositions.

    Two-level: gather each index's 128-wide block with a one-hot f32
    matmul on the MXU (u16 halves keep all of u32 exact), then select
    within the block. idx must be in [0, W)."""
    r, w = table.shape
    table = table.astype(jnp.uint32)
    if _use_native():
        return jnp.take_along_axis(
            table, jnp.clip(idx.astype(jnp.int32), 0, w - 1), axis=1
        )
    nb = -(-w // blk)
    wp = nb * blk
    if wp != w:
        table = jnp.pad(table, ((0, 0), (0, wp - w)))
    b_idx = jnp.minimum(idx.astype(jnp.int32) // blk, nb - 1)
    onehot_b = (
        b_idx[:, :, None] == jnp.arange(nb)[None, None, :]
    ).astype(jnp.float32)  # [R, M, NB]
    word = block_matmul_gather_u32(table.reshape(r, nb, blk), onehot_b)
    hit = (idx % blk)[:, :, None] == jnp.arange(blk)[None, None, :]
    return jnp.max(jnp.where(hit, word, 0), axis=2)


def exact_u32_apply(dot, t: jax.Array) -> jax.Array:
    """Apply a one-hot f32 contraction ``dot`` (f32 array -> f32 array,
    at most one nonzero selector per output element) to a u32 array
    EXACTLY: the value travels as u16 halves (< 2^24, f32-exact at
    HIGHEST precision) and recombines by shift-OR. The exactness-critical
    idiom lives ONLY here — every one-hot-matmul gather/scatter of u32
    data routes through it."""
    t = t.astype(jnp.uint32)
    return (
        dot((t >> 16).astype(jnp.float32)).astype(jnp.uint32) << 16
    ) | dot((t & jnp.uint32(0xFFFF)).astype(jnp.float32)).astype(
        jnp.uint32
    )


def block_matmul_gather_u32(
    tab: jax.Array,  # u32[R, NB, blk] block-reshaped table
    onehot_b: jax.Array,  # f32[R, M, NB] one-hot block selector
) -> jax.Array:
    """Select each row's chosen 128-wide block with one-hot f32 matmuls
    on the MXU (exact_u32_apply carries the u16-halves exactness)."""

    def dot(x):
        return jnp.einsum(
            "rmb,rbj->rmj", onehot_b, x,
            precision=jax.lax.Precision.HIGHEST,
        )

    return exact_u32_apply(dot, tab)


def table_gather_u32(
    table: jax.Array,  # u32[W] SHARED 1-D table (same for every row)
    idx: jax.Array,  # i32[...] indices in [0, W)
    blk: int = 128,
) -> jax.Array:
    """out[...] = table[idx[...]] without a serialized per-element gather:
    one-hot f32 matmuls select each index's 128-wide block (u16 halves keep
    all of u32 exact), then a compare+reduce picks within the block. Unlike
    rowgather_wide the table is NOT per-row, so the block matmul contracts
    a [..., NB] one-hot against the shared [NB, blk] table — no broadcast
    materialization."""
    w = table.shape[0]
    if _use_native():
        return jnp.take(
            table.astype(jnp.uint32), idx.astype(jnp.int32), mode="clip"
        )
    nb = -(-w // blk)
    wp = nb * blk
    tp = table.astype(jnp.uint32)
    if wp != w:
        tp = jnp.pad(tp, (0, wp - w))
    tp = tp.reshape(nb, blk)
    idx = idx.astype(jnp.int32)
    b_idx = jnp.minimum(idx // blk, nb - 1)
    onehot_b = (
        b_idx[..., None] == jnp.arange(nb)[None, :]
    ).astype(jnp.float32)

    def dot(x):
        return jnp.einsum(
            "...b,bj->...j", onehot_b, x,
            precision=jax.lax.Precision.HIGHEST,
        )

    word = exact_u32_apply(dot, tp)
    hit = (idx % blk)[..., None] == jnp.arange(blk)[None, :]
    return jnp.max(jnp.where(hit, word, 0), axis=-1)


# -- rowsum -------------------------------------------------------------------


def _rowsum_kernel(idx_ref, val_ref, out_ref):
    bn, m = idx_ref.shape
    w = out_ref.shape[1]
    ids = jax.lax.broadcasted_iota(jnp.int32, (_SUB_ROWS, m, w), 2)

    def body(t, _):
        r0 = t * _SUB_ROWS
        hit = idx_ref[pl.ds(r0, _SUB_ROWS), :][:, :, None] == ids
        # Bitcast, not astype: values like 1<<31 must survive the trip, and
        # i32 addition is mod-2^32 identical to u32.
        vi = jax.lax.bitcast_convert_type(
            val_ref[pl.ds(r0, _SUB_ROWS), :], jnp.int32
        )[:, :, None]
        out_ref[pl.ds(r0, _SUB_ROWS), :] = jax.lax.bitcast_convert_type(
            jnp.sum(jnp.where(hit, vi, 0), axis=1), jnp.uint32
        )
        return 0

    jax.lax.fori_loop(0, bn // _SUB_ROWS, body, 0)


def rowsum(
    idx: jax.Array,  # i32[R, M] column index per entry
    val: jax.Array,  # u32[R, M]
    mask: jax.Array | None,  # bool[R, M] live entries (None = all)
    width: int,
) -> jax.Array:
    """out[r, x] = sum (mod 2^32) over masked m with idx[r, m] == x of
    val[r, m]. With each (r, x, bit) contributed at most once, this is a
    row-local scatter-OR — how the gossip window assembles its possession
    bitmasks without a serialized TPU scatter."""
    r, m = idx.shape
    val = val.astype(jnp.uint32)
    if mask is not None:
        idx = jnp.where(mask, idx, -1)
        val = jnp.where(mask, val, 0)
    if _use_native():
        # Native row-local scatter-add (u32 add is mod 2^32 like the
        # dense sum); out-of-range entries drop, matching the dense
        # form's missed compares.
        rows = jnp.arange(r, dtype=jnp.int32)[:, None]
        safe = jnp.where((idx >= 0) & (idx < width), idx, width)
        return (
            jnp.zeros((r, width), jnp.uint32)
            .at[rows, safe]
            .add(val, mode="drop")
        )
    if not _use_pallas(r * m * width):
        ids = jnp.arange(width, dtype=idx.dtype)
        hit = idx[:, None, :] == ids[None, :, None]
        return jnp.sum(jnp.where(hit, val[:, None, :], 0), axis=2)
    bn = _block_rows(m, width)
    rows_p = -(-r // bn) * bn
    out = pl.pallas_call(
        _rowsum_kernel,
        out_shape=jax.ShapeDtypeStruct((rows_p, width), jnp.uint32),
        grid=(rows_p // bn,),
        in_specs=[
            pl.BlockSpec((bn, m), lambda i: (i, 0)),
            pl.BlockSpec((bn, m), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, width), lambda i: (i, 0)),
    )(_pad_rows(idx.astype(jnp.int32), rows_p), _pad_rows(val, rows_p))
    return out[:r]


# -- rowgather ----------------------------------------------------------------


def _rowgather_kernel(table_ref, idx_ref, out_ref):
    bn, w = table_ref.shape
    m = idx_ref.shape[1]
    ids = jax.lax.broadcasted_iota(jnp.int32, (_SUB_ROWS, m, w), 2)

    def body(t, _):
        r0 = t * _SUB_ROWS
        hit = idx_ref[pl.ds(r0, _SUB_ROWS), :][:, :, None] == ids
        ti = _flip(table_ref[pl.ds(r0, _SUB_ROWS), :])[:, None, :]
        out_ref[pl.ds(r0, _SUB_ROWS), :] = _unflip(
            jnp.max(jnp.where(hit, ti, jnp.int32(-(2**31))), axis=2)
        )
        return 0

    jax.lax.fori_loop(0, bn // _SUB_ROWS, body, 0)


def rowgather(table: jax.Array, idx: jax.Array) -> jax.Array:
    """out[r, m] = table[r, idx[r, m]] (idx must be in range; u32 table)."""
    r, width = table.shape
    m = idx.shape[1]
    table = table.astype(jnp.uint32)
    if _use_native():
        # Native row-local gather; out-of-range indices yield 0 like the
        # dense form's missed compare (negatives routed to the fill
        # sentinel — take_along_axis would otherwise wrap them).
        safe = jnp.where(idx < 0, width, idx.astype(jnp.int32))
        return jnp.take_along_axis(
            table, safe, axis=1, mode="fill", fill_value=0
        )
    if not _use_pallas(r * m * width):
        ids = jnp.arange(width, dtype=idx.dtype)
        hit = idx[:, :, None] == ids[None, None, :]
        return jnp.max(jnp.where(hit, table[:, None, :], 0), axis=2)
    bn = _block_rows(m, width)
    rows_p = -(-r // bn) * bn
    out = pl.pallas_call(
        _rowgather_kernel,
        out_shape=jax.ShapeDtypeStruct((rows_p, m), jnp.uint32),
        grid=(rows_p // bn,),
        in_specs=[
            pl.BlockSpec((bn, width), lambda i: (i, 0)),
            pl.BlockSpec((bn, m), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, m), lambda i: (i, 0)),
    )(_pad_rows(table, rows_p), _pad_rows(idx.astype(jnp.int32), rows_p))
    return out[:r]
