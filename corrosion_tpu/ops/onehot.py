"""One-hot row reductions: the data plane's scatter/gather replacement.

Two primitives used throughout the gossip kernels (see ops/gossip.py):

- ``rowmax(idx, val, mask, width)``:  out[r, x] = max over m with
  idx[r, m] == x of val[r, m]   (a row-local scatter-max)
- ``rowgather(table, idx)``:          out[r, m] = table[r, idx[r, m]]
  (a row-local take_along_axis)

Why not scatter/gather? TPU scatters serialize per element (~70M elem/s
measured on v5e — 207 ms for a [100k, 144] scatter into [100k, 512]) and
dynamic gathers lower similarly badly (269 ms). Why not a plain jnp
one-hot broadcast? In context XLA materializes the [R, M, W] compare /
select intermediates to HBM when they have multiple consumers — measured
331 GB of HBM traffic per broadcast round at 100k nodes, ~0.5 s of pure
bandwidth.

On **CPU** the trade inverts completely: XLA:CPU lowers scatter/gather to
tight serial loops (no per-element device round-trip), while the dense
one-hot broadcast does O(R·M·W) compare+select lanes of real work.
Measured at the 512-node bench shapes: ``rowmax`` 318 ms dense vs 9.5 ms
native scatter-max, ``rowgather`` 305 ms dense vs 0.9 ms
``take_along_axis`` — the whole r05 CPU-fallback bench regression in two
primitives.

Every primitive therefore dispatches on a **three-way backend** at trace
time (``resolve_backend``):

- ``native``  — scatter/gather lowerings (auto-selected on CPU);
- ``dense``   — one-hot broadcast / MXU matmul forms (auto-selected on
  accelerators);
- ``pallas``  — hand-written VMEM-tiled kernels with on-chip
  accumulation. The delivery-chain kernels (``delivery_reduce``,
  ``window_delivery``) fuse what the dense path runs as 4-6 separate
  one-hot launches with full [R, W] HBM round-trips between them; the
  gather kernels (``rowgather_wide``, ``table_gather_u32``) replace the
  f32-matmul-halves exactness trick with native u32 compare+max
  accumulation. Off-TPU the kernels run under
  ``pallas_call(..., interpret=True)``, so tier-1 pins bit-equality
  against the other two backends without a TPU.

Results are bit-identical across all three backends (all-integer
max/add/select), which ``tests/test_perf_plane.py`` pins by running every
primitive and whole gossip rounds on each path.

Reference anchor: these implement the batched merge/delivery promotions of
corro-agent's broadcast plane (broadcast/mod.rs:356-567) and the CRDT
scatter-merge (crsql `INSERT INTO crsql_changes` replay, agent.rs:2192-2214)
at simulator scale.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Rows per grid program (amortizes DMA latency) and per inner sub-tile
# (bounds the [sub, M, W] register/VMEM temporary to ~2.4 MB at M=144,
# W=512).
_BLOCK_ROWS = 256
_SUB_ROWS = 8
# Lane width of the on-chip gather blocks (table_gather_u32 /
# rowgather_wide walk the table in blocks of this many columns so the
# per-sub-tile temporary stays register/VMEM-resident at any W).
_GATHER_BLK = 128
# Below this many one-hot lanes (rows·M·width) the jnp broadcast form stays
# in cache/fusion range and beats a kernel launch.
_PALLAS_MIN_LANES = 1 << 27


def _block_rows(m: int, width: int) -> int:
    # Adaptive: keep each [bn, W] VMEM buffer under ~1 MB so wide writer
    # axes (the 10k flagship) still fit several live blocks per program.
    target = (1 << 20) // max(4 * width, 1)
    bn = (target // _SUB_ROWS) * _SUB_ROWS
    return max(_SUB_ROWS, min(_BLOCK_ROWS, bn))


def _use_pallas(lanes: int) -> bool:
    # The LEGACY dense-backend experiment (pre-fusion kernels): measured
    # on v5e at wan_100k shapes, the fused jnp broadcast form beat these
    # kernels (567 vs 651 ms broadcast plane) — XLA's materialized
    # one-hot intermediates still stream at near-HBM bandwidth while the
    # VMEM-tiled kernels are VPU-throughput-bound.
    # CORRO_ONEHOT_PALLAS=1 re-enables for experiments; the supported
    # kernel path is the "pallas" BACKEND (resolve_backend), which fuses
    # the delivery chain instead of launching per-primitive.
    import os

    if os.environ.get("CORRO_ONEHOT_PALLAS", "0") != "1":
        return False
    return jax.default_backend() == "tpu" and lanes >= _PALLAS_MIN_LANES


# -- backend dispatch ---------------------------------------------------------
#
# Trace-time three-way dispatch. Resolution order (first set wins):
# explicit ``backend=`` argument (how GossipConfig.kernel_backend reaches
# the primitives through the engine drivers), the ``_BACKEND`` module
# global, the legacy ``_NATIVE_SCATTER`` bool global (True -> "native",
# False -> "dense" — the PR 5 test convention), the
# ``CORRO_ONEHOT_BACKEND`` env var, then auto: native on CPU, dense on
# accelerators. Flip globals BEFORE tracing, or clear_cache() the jitted
# callers, since the choice is baked in at trace time.

BACKENDS = ("native", "dense", "pallas")

_NATIVE_SCATTER: bool | None = None
_BACKEND: str | None = None


def resolve_backend(override: str | None = None) -> str:
    import os

    for choice in (override, _BACKEND):
        if choice is not None:
            if choice not in BACKENDS:
                raise ValueError(
                    f"unknown onehot backend {choice!r}; expected one of "
                    f"{BACKENDS}"
                )
            return choice
    if _NATIVE_SCATTER is not None:
        return "native" if _NATIVE_SCATTER else "dense"
    env = os.environ.get("CORRO_ONEHOT_BACKEND")
    if env:
        if env not in BACKENDS:
            raise ValueError(
                f"CORRO_ONEHOT_BACKEND={env!r}; expected one of {BACKENDS}"
            )
        return env
    return "native" if jax.default_backend() == "cpu" else "dense"


def _use_native(backend: str | None = None) -> bool:
    return resolve_backend(backend) == "native"


def _interpret() -> bool:
    # Off-TPU the Mosaic lowering is unavailable; interpret mode runs the
    # identical kernel math as XLA ops, so CPU CI pins bit-equality.
    return jax.default_backend() != "tpu"


def _pad_rows(x: jax.Array, rows_p: int):
    r = x.shape[0]
    if rows_p == r:
        return x
    pad = [(0, rows_p - r)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, pad)


def _pad_axis(x: jax.Array, axis: int, size_p: int):
    if x.shape[axis] == size_p:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, size_p - x.shape[axis])
    return jnp.pad(x, pad)


# -- rowmax -------------------------------------------------------------------


def _flip(u32val: jax.Array) -> jax.Array:
    """u32 → i32 preserving order (Mosaic can't reduce unsigned ints);
    u32 0 maps to i32 min, so the 'no entry' floor survives the trip."""
    return (u32val ^ jnp.uint32(1 << 31)).astype(jnp.int32)


def _unflip(i32val: jax.Array) -> jax.Array:
    return i32val.astype(jnp.uint32) ^ jnp.uint32(1 << 31)


def _rowmax_kernel(idx_ref, val_ref, out_ref):
    # Big row blocks amortize per-program DMA latency; the inner loop
    # walks 8-row sub-tiles (sublane-aligned dynamic slices are legal)
    # whose [8, M, W] one-hot temporaries live in registers/VMEM. Nothing
    # reaches HBM but the inputs and the [bn, W] result.
    bn, m = idx_ref.shape
    w = out_ref.shape[1]
    ids = jax.lax.broadcasted_iota(jnp.int32, (_SUB_ROWS, m, w), 2)

    def body(t, _):
        r0 = t * _SUB_ROWS
        hit = idx_ref[pl.ds(r0, _SUB_ROWS), :][:, :, None] == ids
        vi = _flip(val_ref[pl.ds(r0, _SUB_ROWS), :])[:, :, None]
        out_ref[pl.ds(r0, _SUB_ROWS), :] = _unflip(
            jnp.max(jnp.where(hit, vi, jnp.int32(-(2**31))), axis=1)
        )
        return 0

    jax.lax.fori_loop(0, bn // _SUB_ROWS, body, 0)


def _rowmax_pallas(idx, val, width: int):
    r, m = idx.shape
    bn = _block_rows(m, width)
    rows_p = -(-r // bn) * bn
    out = pl.pallas_call(
        _rowmax_kernel,
        out_shape=jax.ShapeDtypeStruct((rows_p, width), jnp.uint32),
        grid=(rows_p // bn,),
        in_specs=[
            pl.BlockSpec((bn, m), lambda i: (i, 0)),
            pl.BlockSpec((bn, m), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, width), lambda i: (i, 0)),
        interpret=_interpret(),
    )(_pad_rows(idx.astype(jnp.int32), rows_p), _pad_rows(val, rows_p))
    return out[:r]


def rowmax(
    idx: jax.Array,  # i32[R, M] column index per entry (any value ok if masked)
    val: jax.Array,  # u32[R, M]
    mask: jax.Array | None,  # bool[R, M] live entries (None = all)
    width: int,
    backend: str | None = None,
) -> jax.Array:
    """out[r, x] = max over masked m with idx[r, m] == x of val[r, m], 0
    when none. Masked/out-of-range entries contribute nothing."""
    r, m = idx.shape
    if r == 0 or m == 0 or width == 0:
        # Degenerate axes: no entry contributes anywhere (what the
        # native scatter produces; the dense reduce and the kernels
        # cannot shape an empty reduction).
        return jnp.zeros((r, width), jnp.uint32)
    val = val.astype(jnp.uint32)
    if mask is not None:
        idx = jnp.where(mask, idx, -1)
        val = jnp.where(mask, val, 0)
    bk = resolve_backend(backend)
    if bk == "native":
        # Native row-local scatter-max. Out-of-range/masked entries route
        # to a dropped sentinel column (scatter mode="drop" ignores them
        # — same contribution as the dense form's missed compare).
        rows = jnp.arange(r, dtype=jnp.int32)[:, None]
        safe = jnp.where((idx >= 0) & (idx < width), idx, width)
        return (
            jnp.zeros((r, width), jnp.uint32)
            .at[rows, safe]
            .max(val, mode="drop")
        )
    if bk == "pallas" or _use_pallas(r * m * width):
        return _rowmax_pallas(idx, val, width)
    # Reduce over the MINOR-MOST axis: [R, W, M] with the M messages
    # last lets XLA fuse the compare+select straight into a row
    # reduction (the [R, M, W] middle-axis form materialized ~30 GB
    # per call at wan_100k shapes).
    ids = jnp.arange(width, dtype=idx.dtype)
    hit = idx[:, None, :] == ids[None, :, None]
    return jnp.max(jnp.where(hit, val[:, None, :], 0), axis=2)


# -- rowgather_wide -----------------------------------------------------------


def _rowgather_wide_kernel(table_ref, idx_ref, out_ref):
    # Per-row WIDE table gather with on-chip accumulation: walk the table
    # in 128-lane blocks so the [sub, M, 128] compare temporary stays in
    # registers/VMEM at any W (the flat [sub, M, W] form would be ~46 MB
    # at the 10k-writer flagship). The accumulator rides the order-
    # preserving i32 flip (Mosaic can't reduce unsigned ints); the i32-min
    # floor unflips to the dense form's 0 when nothing hits.
    bn, w = table_ref.shape
    m = idx_ref.shape[1]
    nb = w // _GATHER_BLK
    ids = jax.lax.broadcasted_iota(
        jnp.int32, (_SUB_ROWS, m, _GATHER_BLK), 2
    )
    floor = jnp.int32(-(2**31))

    def body(t, _):
        r0 = t * _SUB_ROWS
        idx = idx_ref[pl.ds(r0, _SUB_ROWS), :]
        acc = jnp.full((_SUB_ROWS, m), floor, jnp.int32)
        for j in range(nb):  # static unroll: nb is trace-time
            tb = _flip(table_ref[
                pl.ds(r0, _SUB_ROWS), j * _GATHER_BLK : (j + 1) * _GATHER_BLK
            ])
            hit = idx[:, :, None] == ids + jnp.int32(j * _GATHER_BLK)
            acc = jnp.maximum(
                acc, jnp.max(jnp.where(hit, tb[:, None, :], floor), axis=2)
            )
        out_ref[pl.ds(r0, _SUB_ROWS), :] = _unflip(acc)
        return 0

    jax.lax.fori_loop(0, bn // _SUB_ROWS, body, 0)


def rowgather_wide(
    table: jax.Array, idx: jax.Array, blk: int = 128,
    backend: str | None = None,
) -> jax.Array:
    """out[r, m] = table[r, idx[r, m]] for WIDE tables (thousands of
    columns), where both the dense one-hot form (O(R·M·W) lanes) and
    take_along_axis (serialized per-element gather, ~17 ms per 1.4M
    elements on v5e) are losing propositions.

    Dense: gather each index's 128-wide block with a one-hot f32
    matmul on the MXU (u16 halves keep all of u32 exact), then select
    within the block. Pallas: native u32 compare+max accumulation over
    128-lane blocks — no f32 halves. idx must be in [0, W)."""
    r, w = table.shape
    if r == 0 or idx.shape[1] == 0 or w == 0:
        return jnp.zeros((r, idx.shape[1]), jnp.uint32)
    table = table.astype(jnp.uint32)
    bk = resolve_backend(backend)
    if bk == "native":
        return jnp.take_along_axis(
            table, jnp.clip(idx.astype(jnp.int32), 0, w - 1), axis=1
        )
    if bk == "pallas":
        m = idx.shape[1]
        wp = -(-w // _GATHER_BLK) * _GATHER_BLK
        bn = _block_rows(m, wp)
        rows_p = -(-r // bn) * bn
        out = pl.pallas_call(
            _rowgather_wide_kernel,
            out_shape=jax.ShapeDtypeStruct((rows_p, m), jnp.uint32),
            grid=(rows_p // bn,),
            in_specs=[
                pl.BlockSpec((bn, wp), lambda i: (i, 0)),
                pl.BlockSpec((bn, m), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((bn, m), lambda i: (i, 0)),
            interpret=_interpret(),
        )(
            _pad_rows(_pad_axis(table, 1, wp), rows_p),
            _pad_rows(
                jnp.clip(idx.astype(jnp.int32), 0, w - 1), rows_p
            ),
        )
        return out[:r]
    nb = -(-w // blk)
    wp = nb * blk
    if wp != w:
        table = jnp.pad(table, ((0, 0), (0, wp - w)))
    b_idx = jnp.minimum(idx.astype(jnp.int32) // blk, nb - 1)
    onehot_b = (
        b_idx[:, :, None] == jnp.arange(nb)[None, None, :]
    ).astype(jnp.float32)  # [R, M, NB]
    word = block_matmul_gather_u32(table.reshape(r, nb, blk), onehot_b)
    hit = (idx % blk)[:, :, None] == jnp.arange(blk)[None, None, :]
    return jnp.max(jnp.where(hit, word, 0), axis=2)


def exact_u32_apply(dot, t: jax.Array) -> jax.Array:
    """Apply a one-hot f32 contraction ``dot`` (f32 array -> f32 array,
    at most one nonzero selector per output element) to a u32 array
    EXACTLY: the value travels as u16 halves (< 2^24, f32-exact at
    HIGHEST precision) and recombines by shift-OR. The exactness-critical
    idiom lives ONLY here — every one-hot-matmul gather/scatter of u32
    data routes through it. (The ``pallas`` backend does not need it:
    its gather kernels accumulate native u32 on chip.)"""
    t = t.astype(jnp.uint32)
    return (
        dot((t >> 16).astype(jnp.float32)).astype(jnp.uint32) << 16
    ) | dot((t & jnp.uint32(0xFFFF)).astype(jnp.float32)).astype(
        jnp.uint32
    )


def block_matmul_gather_u32(
    tab: jax.Array,  # u32[R, NB, blk] block-reshaped table
    onehot_b: jax.Array,  # f32[R, M, NB] one-hot block selector
) -> jax.Array:
    """Select each row's chosen 128-wide block with one-hot f32 matmuls
    on the MXU (exact_u32_apply carries the u16-halves exactness)."""

    def dot(x):
        return jnp.einsum(
            "rmb,rbj->rmj", onehot_b, x,
            precision=jax.lax.Precision.HIGHEST,
        )

    return exact_u32_apply(dot, tab)


def _table_gather_kernel(table_ref, idx_ref, out_ref):
    # Shared 1-D table gather, native u32: the table rides VMEM once per
    # program and each 128-lane block is compared+max-accumulated on
    # chip — the integer replacement for the f32-matmul-halves form.
    # Accumulation in the order-preserving i32 flip (Mosaic can't reduce
    # unsigned ints); the floor unflips to 0 when nothing hits.
    bn, c = idx_ref.shape
    w = table_ref.shape[1]
    nb = w // _GATHER_BLK
    ids = jax.lax.broadcasted_iota(
        jnp.int32, (_SUB_ROWS, c, _GATHER_BLK), 2
    )
    floor = jnp.int32(-(2**31))

    def body(t, _):
        r0 = t * _SUB_ROWS
        idx = idx_ref[pl.ds(r0, _SUB_ROWS), :]
        acc = jnp.full((_SUB_ROWS, c), floor, jnp.int32)
        for j in range(nb):  # static unroll
            tb = _flip(table_ref[0, j * _GATHER_BLK : (j + 1) * _GATHER_BLK])
            hit = idx[:, :, None] == ids + jnp.int32(j * _GATHER_BLK)
            acc = jnp.maximum(
                acc,
                jnp.max(jnp.where(hit, tb[None, None, :], floor), axis=2),
            )
        out_ref[pl.ds(r0, _SUB_ROWS), :] = _unflip(acc)
        return 0

    jax.lax.fori_loop(0, bn // _SUB_ROWS, body, 0)


def table_gather_u32(
    table: jax.Array,  # u32[W] SHARED 1-D table (same for every row)
    idx: jax.Array,  # i32[...] indices in [0, W)
    blk: int = 128,
    backend: str | None = None,
) -> jax.Array:
    """out[...] = table[idx[...]] without a serialized per-element gather.

    Dense: one-hot f32 matmuls select each index's 128-wide block (u16
    halves keep all of u32 exact), then a compare+reduce picks within the
    block. Pallas: native u32 compare+max over 128-lane table blocks with
    on-chip accumulation. Unlike rowgather_wide the table is NOT per-row,
    so the block matmul contracts a [..., NB] one-hot against the shared
    [NB, blk] table — no broadcast materialization."""
    w = table.shape[0]
    if w == 0 or idx.size == 0:
        return jnp.zeros(idx.shape, jnp.uint32)
    bk = resolve_backend(backend)
    if bk == "native":
        return jnp.take(
            table.astype(jnp.uint32), idx.astype(jnp.int32), mode="clip"
        )
    if bk == "pallas":
        shape = idx.shape
        flat = jnp.clip(
            idx.astype(jnp.int32).reshape(-1), 0, w - 1
        )
        p = flat.shape[0]
        cols = _GATHER_BLK
        rows = max(1, -(-p // cols))
        bn = max(_SUB_ROWS, min(_BLOCK_ROWS, -(-rows // _SUB_ROWS) * _SUB_ROWS))
        rows_p = -(-rows // bn) * bn
        flat = jnp.pad(flat, (0, rows_p * cols - p)).reshape(rows_p, cols)
        wp = -(-w // _GATHER_BLK) * _GATHER_BLK
        tp = _pad_axis(table.astype(jnp.uint32), 0, wp)[None, :]
        out = pl.pallas_call(
            _table_gather_kernel,
            out_shape=jax.ShapeDtypeStruct((rows_p, cols), jnp.uint32),
            grid=(rows_p // bn,),
            in_specs=[
                pl.BlockSpec((1, wp), lambda i: (0, 0)),
                pl.BlockSpec((bn, cols), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((bn, cols), lambda i: (i, 0)),
            interpret=_interpret(),
        )(tp, flat)
        return out.reshape(-1)[:p].reshape(shape)
    nb = -(-w // blk)
    wp = nb * blk
    tp = table.astype(jnp.uint32)
    if wp != w:
        tp = jnp.pad(tp, (0, wp - w))
    tp = tp.reshape(nb, blk)
    idx = idx.astype(jnp.int32)
    b_idx = jnp.minimum(idx // blk, nb - 1)
    onehot_b = (
        b_idx[..., None] == jnp.arange(nb)[None, :]
    ).astype(jnp.float32)

    def dot(x):
        return jnp.einsum(
            "...b,bj->...j", onehot_b, x,
            precision=jax.lax.Precision.HIGHEST,
        )

    word = exact_u32_apply(dot, tp)
    hit = (idx % blk)[..., None] == jnp.arange(blk)[None, :]
    return jnp.max(jnp.where(hit, word, 0), axis=-1)


# -- rowsum -------------------------------------------------------------------


def _rowsum_kernel(idx_ref, val_ref, out_ref):
    bn, m = idx_ref.shape
    w = out_ref.shape[1]
    ids = jax.lax.broadcasted_iota(jnp.int32, (_SUB_ROWS, m, w), 2)

    def body(t, _):
        r0 = t * _SUB_ROWS
        hit = idx_ref[pl.ds(r0, _SUB_ROWS), :][:, :, None] == ids
        # Bitcast, not astype: values like 1<<31 must survive the trip, and
        # i32 addition is mod-2^32 identical to u32.
        vi = jax.lax.bitcast_convert_type(
            val_ref[pl.ds(r0, _SUB_ROWS), :], jnp.int32
        )[:, :, None]
        out_ref[pl.ds(r0, _SUB_ROWS), :] = jax.lax.bitcast_convert_type(
            jnp.sum(jnp.where(hit, vi, 0), axis=1), jnp.uint32
        )
        return 0

    jax.lax.fori_loop(0, bn // _SUB_ROWS, body, 0)


def rowsum(
    idx: jax.Array,  # i32[R, M] column index per entry
    val: jax.Array,  # u32[R, M]
    mask: jax.Array | None,  # bool[R, M] live entries (None = all)
    width: int,
    backend: str | None = None,
) -> jax.Array:
    """out[r, x] = sum (mod 2^32) over masked m with idx[r, m] == x of
    val[r, m]. With each (r, x, bit) contributed at most once, this is a
    row-local scatter-OR — how the gossip window assembles its possession
    bitmasks without a serialized TPU scatter."""
    r, m = idx.shape
    if r == 0 or m == 0 or width == 0:
        return jnp.zeros((r, width), jnp.uint32)
    val = val.astype(jnp.uint32)
    if mask is not None:
        idx = jnp.where(mask, idx, -1)
        val = jnp.where(mask, val, 0)
    bk = resolve_backend(backend)
    if bk == "native":
        # Native row-local scatter-add (u32 add is mod 2^32 like the
        # dense sum); out-of-range entries drop, matching the dense
        # form's missed compares.
        rows = jnp.arange(r, dtype=jnp.int32)[:, None]
        safe = jnp.where((idx >= 0) & (idx < width), idx, width)
        return (
            jnp.zeros((r, width), jnp.uint32)
            .at[rows, safe]
            .add(val, mode="drop")
        )
    if bk == "pallas" or _use_pallas(r * m * width):
        bn = _block_rows(m, width)
        rows_p = -(-r // bn) * bn
        out = pl.pallas_call(
            _rowsum_kernel,
            out_shape=jax.ShapeDtypeStruct((rows_p, width), jnp.uint32),
            grid=(rows_p // bn,),
            in_specs=[
                pl.BlockSpec((bn, m), lambda i: (i, 0)),
                pl.BlockSpec((bn, m), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((bn, width), lambda i: (i, 0)),
            interpret=_interpret(),
        )(_pad_rows(idx.astype(jnp.int32), rows_p), _pad_rows(val, rows_p))
        return out[:r]
    ids = jnp.arange(width, dtype=idx.dtype)
    hit = idx[:, None, :] == ids[None, :, None]
    return jnp.sum(jnp.where(hit, val[:, None, :], 0), axis=2)


# -- rowgather ----------------------------------------------------------------


def _rowgather_kernel(table_ref, idx_ref, out_ref):
    bn, w = table_ref.shape
    m = idx_ref.shape[1]
    ids = jax.lax.broadcasted_iota(jnp.int32, (_SUB_ROWS, m, w), 2)

    def body(t, _):
        r0 = t * _SUB_ROWS
        hit = idx_ref[pl.ds(r0, _SUB_ROWS), :][:, :, None] == ids
        ti = _flip(table_ref[pl.ds(r0, _SUB_ROWS), :])[:, None, :]
        out_ref[pl.ds(r0, _SUB_ROWS), :] = _unflip(
            jnp.max(jnp.where(hit, ti, jnp.int32(-(2**31))), axis=2)
        )
        return 0

    jax.lax.fori_loop(0, bn // _SUB_ROWS, body, 0)


def _rowgather_pallas(table, idx):
    r, width = table.shape
    m = idx.shape[1]
    bn = _block_rows(m, width)
    rows_p = -(-r // bn) * bn
    out = pl.pallas_call(
        _rowgather_kernel,
        out_shape=jax.ShapeDtypeStruct((rows_p, m), jnp.uint32),
        grid=(rows_p // bn,),
        in_specs=[
            pl.BlockSpec((bn, width), lambda i: (i, 0)),
            pl.BlockSpec((bn, m), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, m), lambda i: (i, 0)),
        interpret=_interpret(),
    )(_pad_rows(table, rows_p), _pad_rows(idx.astype(jnp.int32), rows_p))
    return out[:r]


def rowgather(
    table: jax.Array, idx: jax.Array, backend: str | None = None
) -> jax.Array:
    """out[r, m] = table[r, idx[r, m]] (idx must be in range; u32 table)."""
    r, width = table.shape
    m = idx.shape[1]
    if r == 0 or m == 0 or width == 0:
        return jnp.zeros((r, m), jnp.uint32)
    table = table.astype(jnp.uint32)
    bk = resolve_backend(backend)
    if bk == "native":
        # Native row-local gather; out-of-range indices yield 0 like the
        # dense form's missed compare (negatives routed to the fill
        # sentinel — take_along_axis would otherwise wrap them).
        safe = jnp.where(idx < 0, width, idx.astype(jnp.int32))
        return jnp.take_along_axis(
            table, safe, axis=1, mode="fill", fill_value=0
        )
    if bk == "pallas" or _use_pallas(r * m * width):
        return _rowgather_pallas(table, idx)
    ids = jnp.arange(width, dtype=idx.dtype)
    hit = idx[:, :, None] == ids[None, None, :]
    return jnp.max(jnp.where(hit, table[:, None, :], 0), axis=2)


# -- fused delivery-chain kernels ---------------------------------------------
#
# The broadcast-round delivery chain (ops/gossip.py, fast path) runs, per
# round: rowmax of applied deltas (the watermark advance), rowmax of
# arriving versions folded into `seen`, then — under out-of-order windows
# — a per-word rowgather of prior possession and a per-word rowsum
# assembling the new possession bits. As separate one-hot launches each
# re-materializes the [sub, M, W] compare block and round-trips the
# [R, W] planes through HBM. The two kernels below fuse the chain: the
# compare block (`hit`) is computed once per sub-tile and reused across
# every reduction, and the [tile, W] accumulators live in VMEM for the
# whole chain. The non-pallas composition of the SAME primitives is the
# bit-identical tested reference (the `_BATCHED_SYNC` pattern).


def _delivery_reduce_kernel(
    idx_a_ref, val_a_ref, idx_v_ref, val_v_ref, seen_ref,
    adv_ref, seen_out_ref,
):
    bn, m = idx_a_ref.shape
    w = adv_ref.shape[1]
    ids = jax.lax.broadcasted_iota(jnp.int32, (_SUB_ROWS, m, w), 2)
    floor = jnp.int32(-(2**31))

    def body(t, _):
        r0 = t * _SUB_ROWS
        # One pass, two accumulators: the applied-delta max (the
        # watermark advance) and the heard-version max folded into
        # `seen` — both [sub, W] planes stay on chip between them.
        hit_a = idx_a_ref[pl.ds(r0, _SUB_ROWS), :][:, :, None] == ids
        va = _flip(val_a_ref[pl.ds(r0, _SUB_ROWS), :])[:, :, None]
        adv_ref[pl.ds(r0, _SUB_ROWS), :] = _unflip(
            jnp.max(jnp.where(hit_a, va, floor), axis=1)
        )
        hit_v = idx_v_ref[pl.ds(r0, _SUB_ROWS), :][:, :, None] == ids
        vv = _flip(val_v_ref[pl.ds(r0, _SUB_ROWS), :])[:, :, None]
        seen_out_ref[pl.ds(r0, _SUB_ROWS), :] = jnp.maximum(
            seen_ref[pl.ds(r0, _SUB_ROWS), :],
            _unflip(jnp.max(jnp.where(hit_v, vv, floor), axis=1)),
        )
        return 0

    jax.lax.fori_loop(0, bn // _SUB_ROWS, body, 0)


def delivery_reduce(
    idx: jax.Array,  # i32[R, M] writer column per sorted message
    d: jax.Array,  # u32[R, M] delta above the pre-round watermark
    v: jax.Array,  # u32[R, M] absolute version
    applied: jax.Array,  # bool[R, M] messages on an unbroken run
    valid: jax.Array,  # bool[R, M] live messages
    seen: jax.Array,  # u32[R, W] highest version heard of
    width: int,
    backend: str | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Fused delivery reductions: ``(advance, seen')`` where
    ``advance = rowmax(idx, d, applied, W)`` and
    ``seen' = max(seen, rowmax(idx, v, valid, W))`` — one VMEM pass under
    the pallas backend, the two-primitive composition elsewhere (the
    bit-identical reference)."""
    if idx.shape[0] == 0 or idx.shape[1] == 0 or width == 0:
        return (
            jnp.zeros((idx.shape[0], width), jnp.uint32),
            seen.astype(jnp.uint32),
        )
    bk = resolve_backend(backend)
    if bk != "pallas":
        adv = rowmax(idx, d, applied, width, backend=bk)
        return adv, jnp.maximum(
            seen, rowmax(idx, v, valid, width, backend=bk)
        )
    r, m = idx.shape
    idx_a = jnp.where(applied, idx, -1)
    val_a = jnp.where(applied, d.astype(jnp.uint32), 0)
    idx_v = jnp.where(valid, idx, -1)
    val_v = jnp.where(valid, v.astype(jnp.uint32), 0)
    bn = _block_rows(m, width)
    rows_p = -(-r // bn) * bn
    adv, seen2 = pl.pallas_call(
        _delivery_reduce_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((rows_p, width), jnp.uint32),
            jax.ShapeDtypeStruct((rows_p, width), jnp.uint32),
        ),
        grid=(rows_p // bn,),
        in_specs=[
            pl.BlockSpec((bn, m), lambda i: (i, 0)),
            pl.BlockSpec((bn, m), lambda i: (i, 0)),
            pl.BlockSpec((bn, m), lambda i: (i, 0)),
            pl.BlockSpec((bn, m), lambda i: (i, 0)),
            pl.BlockSpec((bn, width), lambda i: (i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((bn, width), lambda i: (i, 0)),
            pl.BlockSpec((bn, width), lambda i: (i, 0)),
        ),
        interpret=_interpret(),
    )(
        _pad_rows(idx_a.astype(jnp.int32), rows_p),
        _pad_rows(val_a, rows_p),
        _pad_rows(idx_v.astype(jnp.int32), rows_p),
        _pad_rows(val_v, rows_p),
        _pad_rows(seen.astype(jnp.uint32), rows_p),
    )
    return adv[:r], seen2[:r]


def _window_delivery_kernel(
    oo_ref, w2_ref, d_ref, advm_ref, valid_ref, poss_ref, words_ref,
    *, wk: int,
):
    b_words = oo_ref.shape[0]
    bn, m = w2_ref.shape
    w = words_ref.shape[2]
    ids = jax.lax.broadcasted_iota(jnp.int32, (_SUB_ROWS, m, w), 2)

    def body(t, _):
        r0 = t * _SUB_ROWS
        w2 = w2_ref[pl.ds(r0, _SUB_ROWS), :]
        d = d_ref[pl.ds(r0, _SUB_ROWS), :]
        advm = advm_ref[pl.ds(r0, _SUB_ROWS), :]
        valid = valid_ref[pl.ds(r0, _SUB_ROWS), :] != 0
        # ONE compare block feeds every gather and scatter below — the
        # separate-launch form recomputes it 2B times and round-trips
        # each [R, W] word plane through HBM in between.
        hit = w2[:, :, None] == ids
        d_rel = d - advm  # meaningful only when d > advm
        in_win = valid & (d > advm) & (d_rel <= jnp.uint32(wk))
        # Already possessed in the OLD window (bit d-1 above contig_pre)?
        bit_old = d - jnp.uint32(1)
        prev = jnp.zeros((_SUB_ROWS, m), bool)
        for b in range(b_words):
            # Gather rides the order-preserving i32 flip (Mosaic can't
            # reduce unsigned ints — window words routinely set bit 31).
            word = _unflip(jnp.max(
                jnp.where(
                    hit,
                    _flip(oo_ref[b, pl.ds(r0, _SUB_ROWS), :])[:, None, :],
                    jnp.int32(-(2**31)),
                ),
                axis=2,
            ))
            sh = jnp.minimum(
                bit_old - jnp.uint32(32 * b), jnp.uint32(31)
            )
            inb = (bit_old >= jnp.uint32(32 * b)) & (
                bit_old < jnp.uint32(32 * (b + 1))
            )
            prev = prev | (
                inb & (((word >> sh) & jnp.uint32(1)) == jnp.uint32(1))
            )
        new_poss = in_win & ~prev
        poss_ref[pl.ds(r0, _SUB_ROWS), :] = new_poss.astype(jnp.uint32)
        bit_new = d_rel - jnp.uint32(1)
        for b in range(b_words):
            sh = jnp.minimum(
                bit_new - jnp.uint32(32 * b), jnp.uint32(31)
            )
            inb = new_poss & (bit_new >= jnp.uint32(32 * b)) & (
                bit_new < jnp.uint32(32 * (b + 1))
            )
            contrib = jax.lax.bitcast_convert_type(
                jnp.where(inb, jnp.uint32(1) << sh, jnp.uint32(0)),
                jnp.int32,
            )[:, :, None]
            words_ref[b, pl.ds(r0, _SUB_ROWS), :] = (
                jax.lax.bitcast_convert_type(
                    jnp.sum(jnp.where(hit, contrib, 0), axis=1),
                    jnp.uint32,
                )
            )
        return 0

    jax.lax.fori_loop(0, bn // _SUB_ROWS, body, 0)


def window_delivery(
    oo: jax.Array,  # u32[B, R, W] out-of-order window words
    idx: jax.Array,  # i32[R, M] writer column per message (in range)
    d: jax.Array,  # u32[R, M] delta above the pre-round watermark
    adv_m: jax.Array,  # u32[R, M] per-message in-order advance
    valid: jax.Array,  # bool[R, M] live, deduped messages
    wk: int,
    width: int,
    backend: str | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Fused out-of-order admission for the delivery fast path: decide
    which arrivals land in the window (not already possessed, within
    ``wk`` of the advance) and assemble their possession bits. Returns
    ``(new_poss bool[R, M], new_bits u32[B, R, W])`` for
    ``gossip.window_absorb``. Under the pallas backend the per-word
    gather, the old-bit check, and the per-word bit assembly share one
    VMEM compare block; elsewhere the rowgather/rowsum composition below
    is the bit-identical reference."""
    b_words = oo.shape[0]
    if idx.shape[0] == 0 or idx.shape[1] == 0 or width == 0:
        return (
            jnp.zeros(idx.shape, bool),
            jnp.zeros((b_words,) + oo.shape[1:], jnp.uint32),
        )
    bk = resolve_backend(backend)
    if bk != "pallas":
        d_rel = d - adv_m
        in_win = valid & (d > adv_m) & (d_rel <= jnp.uint32(wk))
        bit_old = d - jnp.uint32(1)
        prev_poss = jnp.zeros_like(in_win)
        for b in range(b_words):
            wordv = rowgather(oo[b], idx, backend=bk)
            sh = jnp.minimum(
                bit_old - jnp.uint32(32 * b), jnp.uint32(31)
            )
            inb = (bit_old >= jnp.uint32(32 * b)) & (
                bit_old < jnp.uint32(32 * (b + 1))
            )
            prev_poss = prev_poss | (
                inb & (((wordv >> sh) & jnp.uint32(1)) == jnp.uint32(1))
            )
        new_poss = in_win & ~prev_poss
        bit_new = d_rel - jnp.uint32(1)
        words = []
        for b in range(b_words):
            sh = jnp.minimum(
                bit_new - jnp.uint32(32 * b), jnp.uint32(31)
            )
            inb = new_poss & (bit_new >= jnp.uint32(32 * b)) & (
                bit_new < jnp.uint32(32 * (b + 1))
            )
            words.append(
                rowsum(
                    idx,
                    jnp.where(inb, jnp.uint32(1) << sh, jnp.uint32(0)),
                    None,
                    width,
                    backend=bk,
                )
            )
        return new_poss, jnp.stack(words)
    r, m = idx.shape
    bn = _block_rows(m, width)
    rows_p = -(-r // bn) * bn
    poss, words = pl.pallas_call(
        partial(_window_delivery_kernel, wk=wk),
        out_shape=(
            jax.ShapeDtypeStruct((rows_p, m), jnp.uint32),
            jax.ShapeDtypeStruct((b_words, rows_p, width), jnp.uint32),
        ),
        grid=(rows_p // bn,),
        in_specs=[
            pl.BlockSpec((b_words, bn, width), lambda i: (0, i, 0)),
            pl.BlockSpec((bn, m), lambda i: (i, 0)),
            pl.BlockSpec((bn, m), lambda i: (i, 0)),
            pl.BlockSpec((bn, m), lambda i: (i, 0)),
            pl.BlockSpec((bn, m), lambda i: (i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((bn, m), lambda i: (i, 0)),
            pl.BlockSpec((b_words, bn, width), lambda i: (0, i, 0)),
        ),
        interpret=_interpret(),
    )(
        _pad_axis(oo.astype(jnp.uint32), 1, rows_p),
        _pad_rows(idx.astype(jnp.int32), rows_p),
        _pad_rows(d.astype(jnp.uint32), rows_p),
        _pad_rows(adv_m.astype(jnp.uint32), rows_p),
        _pad_rows(valid.astype(jnp.int32), rows_p),
    )
    return (poss[:r] != 0), words[:, :r]
