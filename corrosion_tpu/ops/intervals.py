"""Fixed-capacity interval-set tensors.

TPU-native equivalent of the `rangemap` RangeInclusiveSet the reference uses
for version/seq bookkeeping (reference corro-types/src/agent.rs:945-1052,
sync.rs:123-246). JAX needs static shapes, so a set of inclusive integer
ranges is a pair of int32 vectors ``(starts, ends)`` of fixed capacity C,
sorted ascending by start, disjoint and non-adjacent, with empty slots pushed
to the back holding the sentinel ``(EMPTY, EMPTY - 1)``.

All functions are pure, jit-safe, and operate on a single set; batch with
``jax.vmap``. Capacity overflow is resolved by dropping the *smallest*
interval ("forget coverage"), which is the safe direction for every use in
this codebase: these sets track data a node *has*, so under-approximating
coverage only causes an idempotent re-fetch/re-merge (CRDT application is
idempotent), never data loss. Property tests in tests/test_ops_intervals.py
check agreement with the host-side ``corrosion_tpu.core.intervals.RangeSet``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

# Sentinel start for an empty slot: huge so empty slots sort last. Kept two
# below int32 max so that ``start - 1`` / ``end + 1`` arithmetic never wraps.
EMPTY = jnp.int32(2**31 - 4)
_BIG_LEN = jnp.int32(2**31 - 1)


class IntervalSet(NamedTuple):
    """Sorted, coalesced, capacity-bounded set of inclusive int32 ranges."""

    starts: jax.Array  # i32[C]
    ends: jax.Array  # i32[C]

    @property
    def capacity(self) -> int:
        return self.starts.shape[-1]


def make(capacity: int) -> IntervalSet:
    return IntervalSet(
        starts=jnp.full((capacity,), EMPTY, dtype=jnp.int32),
        ends=jnp.full((capacity,), EMPTY - 1, dtype=jnp.int32),
    )


def from_ranges(ranges, capacity: int) -> IntervalSet:
    """Host-side constructor from [(start, end), ...] (not jit-traceable)."""
    iv = make(capacity)
    for s, e in ranges:
        iv = insert(iv, jnp.int32(s), jnp.int32(e))
    return iv


def slot_mask(iv: IntervalSet) -> jax.Array:
    """bool[C] — which slots hold a real interval."""
    return iv.starts <= iv.ends


def count(iv: IntervalSet) -> jax.Array:
    return jnp.sum(slot_mask(iv).astype(jnp.int32))


def total(iv: IntervalSet) -> jax.Array:
    """Number of integers covered by the set."""
    m = slot_mask(iv)
    return jnp.sum(jnp.where(m, iv.ends - iv.starts + 1, 0))


def is_empty(iv: IntervalSet) -> jax.Array:
    return ~jnp.any(slot_mask(iv))


def max_end(iv: IntervalSet) -> jax.Array:
    """Largest covered integer, or -1 when empty."""
    m = slot_mask(iv)
    return jnp.max(jnp.where(m, iv.ends, -1))


def min_start(iv: IntervalSet) -> jax.Array:
    """Smallest covered integer, or EMPTY when empty."""
    return jnp.min(iv.starts)


def contains(iv: IntervalSet, x: jax.Array) -> jax.Array:
    return jnp.any(slot_mask(iv) & (iv.starts <= x) & (x <= iv.ends))


def contains_range(iv: IntervalSet, s: jax.Array, e: jax.Array) -> jax.Array:
    """True iff [s, e] lies entirely inside one interval of the set."""
    return jnp.any(slot_mask(iv) & (iv.starts <= s) & (e <= iv.ends))


def _sorted_by_start(starts: jax.Array, ends: jax.Array):
    order = jnp.argsort(starts)
    return starts[order], ends[order]


def _compact(
    starts: jax.Array, ends: jax.Array, capacity: int, max_extra: int = 1
) -> IntervalSet:
    """Sort candidate slots, resolve overflow by dropping smallest intervals.

    ``max_extra`` bounds how far the live count can exceed ``capacity``:
    insert adds one merged slot, and remove can split at most one interval in
    two (intervals are disjoint, so only one can span both cut edges) — both
    are 1. The drop loop unrolls that many times, keeping the jitted kernel
    small.
    """
    valid = starts <= ends
    starts = jnp.where(valid, starts, EMPTY)
    ends = jnp.where(valid, ends, EMPTY - 1)
    for _ in range(max(1, max_extra)):
        live = starts <= ends
        overflow = jnp.sum(live.astype(jnp.int32)) > capacity
        lengths = jnp.where(live, ends - starts + 1, _BIG_LEN)
        drop = jnp.argmin(lengths)
        kill = overflow & (jnp.arange(starts.shape[-1]) == drop)
        starts = jnp.where(kill, EMPTY, starts)
        ends = jnp.where(kill, EMPTY - 1, ends)
    starts, ends = _sorted_by_start(starts, ends)
    return IntervalSet(starts[:capacity], ends[:capacity])


@jax.jit
def insert(iv: IntervalSet, s: jax.Array, e: jax.Array) -> IntervalSet:
    """Insert [s, e], coalescing overlapping and adjacent intervals.

    Matches RangeSet.insert (core/intervals.py) / rangemap semantics.
    """
    s = jnp.int32(s)
    e = jnp.int32(e)
    m = slot_mask(iv)
    # Overlapping or adjacent: start <= e+1 and end >= s-1.
    touch = m & (iv.starts <= e + 1) & (iv.ends >= s - 1)
    merged_s = jnp.minimum(s, jnp.min(jnp.where(touch, iv.starts, EMPTY)))
    merged_e = jnp.maximum(e, jnp.max(jnp.where(touch, iv.ends, -(2**31) + 1)))
    keep_s = jnp.where(touch, EMPTY, iv.starts)
    keep_e = jnp.where(touch, EMPTY - 1, iv.ends)
    cat_s = jnp.concatenate([keep_s, merged_s[None]])
    cat_e = jnp.concatenate([keep_e, merged_e[None]])
    return _compact(cat_s, cat_e, iv.capacity)


@jax.jit
def remove(iv: IntervalSet, s: jax.Array, e: jax.Array) -> IntervalSet:
    """Remove [s, e]; an interval spanning both edges splits in two."""
    s = jnp.int32(s)
    e = jnp.int32(e)
    m = slot_mask(iv)
    left_s = iv.starts
    left_e = jnp.minimum(iv.ends, s - 1)
    lv = m & (left_s <= left_e)
    right_s = jnp.maximum(iv.starts, e + 1)
    right_e = iv.ends
    rv = m & (right_s <= right_e)
    cat_s = jnp.concatenate(
        [jnp.where(lv, left_s, EMPTY), jnp.where(rv, right_s, EMPTY)]
    )
    cat_e = jnp.concatenate(
        [jnp.where(lv, left_e, EMPTY - 1), jnp.where(rv, right_e, EMPTY - 1)]
    )
    return _compact(cat_s, cat_e, iv.capacity)


@jax.jit
def gaps(iv: IntervalSet, s: jax.Array, e: jax.Array) -> IntervalSet:
    """Sub-ranges of [s, e] NOT covered by the set (capacity C+1).

    The TPU analogue of RangeSet.gaps — this is what sync-need computation
    runs on (reference corro-types/src/sync.rs:123-246).
    """
    s = jnp.int32(s)
    e = jnp.int32(e)
    m = slot_mask(iv)
    # Clip the set to the window; only intersecting slots participate.
    inter = m & (iv.starts <= e) & (iv.ends >= s)
    cs = jnp.where(inter, jnp.maximum(iv.starts, s), EMPTY)
    ce = jnp.where(inter, jnp.minimum(iv.ends, e), EMPTY - 1)
    cs, ce = _sorted_by_start(cs, ce)
    # Gap i sits between clipped slot i-1 and clipped slot i; plus tail gap.
    c = iv.capacity
    prev_end = jnp.concatenate([(s - 1)[None], ce])  # [C+1]
    next_start = jnp.concatenate([cs, (e + 1)[None]])  # [C+1]
    g_s = prev_end + 1
    g_e = next_start - 1
    # Beyond the last clipped slot, prev_end is a sentinel; the tail gap is
    # handled by pairing the LAST real slot with e+1. Empty clipped slots have
    # cs=EMPTY which makes interior "gaps" after the run invalid except the
    # first one (the tail gap), which pairs sentinel prev_end... so compute the
    # tail explicitly instead: mark pair (i-1 real or i==0, i real or first
    # empty).
    n_real = jnp.sum(inter.astype(jnp.int32))
    idx = jnp.arange(c + 1)
    pair_ok = idx <= n_real  # gaps before each real slot + one tail gap
    g_e = jnp.where(idx == n_real, e, g_e)  # tail gap ends at e
    valid = pair_ok & (g_s <= g_e)
    out_s, out_e = _sorted_by_start(
        jnp.where(valid, g_s, EMPTY).astype(jnp.int32),
        jnp.where(valid, g_e, EMPTY - 1).astype(jnp.int32),
    )
    return IntervalSet(out_s, out_e)


@jax.jit
def union(a: IntervalSet, b: IntervalSet) -> IntervalSet:
    """a ∪ b at a's capacity (scan-inserts each interval of b)."""

    def body(acc, se):
        s, e = se
        real = s <= e
        return jax.lax.cond(
            real, lambda t: insert(t, s, e), lambda t: t, acc
        ), None

    out, _ = jax.lax.scan(body, a, (b.starts, b.ends))
    return out


@jax.jit
def contiguous_watermark(iv: IntervalSet, base: jax.Array) -> jax.Array:
    """Highest v such that [base, v] is fully covered (or base-1 if none).

    Used for seq-gap tracking: a partial changeset becomes applicable when the
    watermark reaches last_seq (reference agent.rs:2063-2151).
    """
    base = jnp.int32(base)
    wm = base - 1
    # Walk sorted slots once; each covering-or-adjacent slot extends the
    # watermark (slots are sorted by start, so one pass suffices).
    def body(w, se):
        s, e = se
        w = jnp.where((s <= w + 1) & (e > w), e, w)
        return w, None

    wm, _ = jax.lax.scan(body, wm, (iv.starts, iv.ends))
    return wm


# corro-lint: disable=CT004 reason=host materialization; device_get first
def to_host(iv: IntervalSet) -> list[tuple[int, int]]:
    """Materialize as a python list (testing/debug)."""
    starts = jax.device_get(iv.starts)
    ends = jax.device_get(iv.ends)
    return [(int(s), int(e)) for s, e in zip(starts, ends) if s <= e]
