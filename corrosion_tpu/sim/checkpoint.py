"""Simulation checkpoint / resume + schedule (trace) persistence.

The reference checkpoints by construction — all replication state lives in
SQLite and rehydrates at boot (SURVEY.md §5, agent.rs:147-268). The sim
analogue: a ClusterState snapshot plus the scripted Schedule IS a
replayable trace. `simulate(state=...)` already chains runs and folds the
absolute round index into each round's RNG key, so a save/resume sequence
is bit-identical to an uninterrupted run (asserted in tests).

Format: one .npz per snapshot — flat leaves keyed by pytree path, plus the
structure fingerprint so loading against a mismatched config fails loudly
instead of mis-zipping arrays.
"""

from __future__ import annotations

import json

import jax
import numpy as np

from corrosion_tpu.sim.engine import ClusterState, Schedule, init_cluster


def _paths(tree) -> list[str]:
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [jax.tree_util.keystr(path) for path, _ in flat]


def save_state(path: str, state: ClusterState) -> None:
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(state)[0]
    arrays = {
        f"leaf{idx}": np.asarray(leaf)
        for idx, (_, leaf) in enumerate(leaves_with_paths)
    }
    arrays["__paths__"] = np.array(
        json.dumps(_paths(state)).encode()
    )
    np.savez_compressed(path, **arrays)


def load_state(path: str, cfg, n_samples: int) -> ClusterState:
    """Load a snapshot written by ``save_state``; ``cfg``/``n_samples``
    must describe the same cluster (shape + kernel selection)."""
    with np.load(path) as data:
        saved_paths = json.loads(bytes(data["__paths__"].item()).decode())
        template = init_cluster(cfg, n_samples)
        tmpl_paths = _paths(template)
        if saved_paths != tmpl_paths:
            raise ValueError(
                "checkpoint structure does not match the config "
                f"(saved {len(saved_paths)} leaves, config implies "
                f"{len(tmpl_paths)}); was it written with a different "
                "SwimConfig/GossipConfig?"
            )
        leaves = []
        for idx, (tmpl_leaf, p) in enumerate(
            zip(jax.tree.leaves(template), tmpl_paths)
        ):
            arr = data[f"leaf{idx}"]
            if arr.shape != tmpl_leaf.shape:
                raise ValueError(
                    f"checkpoint leaf {p} has shape {arr.shape}, "
                    f"config implies {tmpl_leaf.shape}"
                )
            if arr.dtype != tmpl_leaf.dtype:
                raise ValueError(
                    f"checkpoint leaf {p} has dtype {arr.dtype}, "
                    f"config implies {tmpl_leaf.dtype}"
                )
            leaves.append(arr)
        treedef = jax.tree.structure(template)
        return jax.tree.unflatten(treedef, leaves)


def save_schedule(path: str, schedule: Schedule) -> None:
    arrays = {"writes": schedule.writes}
    # Chaos axes (loss/probe_loss/wipe, sim/faults.py) persist alongside
    # the churn/partition masks: a resumed run replays its fault plan.
    for name in ("kill", "revive", "partition", "loss", "probe_loss",
                 "wipe"):
        v = getattr(schedule, name)
        if v is not None:
            arrays[name] = v
    arrays["sample_writer"] = schedule.sample_writer
    arrays["sample_ver"] = schedule.sample_ver
    arrays["sample_round"] = schedule.sample_round
    np.savez_compressed(path, **arrays)


def load_schedule(path: str) -> Schedule:
    with np.load(path) as data:
        return Schedule(
            writes=data["writes"],
            kill=data["kill"] if "kill" in data else None,
            revive=data["revive"] if "revive" in data else None,
            partition=data["partition"] if "partition" in data else None,
            sample_writer=data["sample_writer"],
            sample_ver=data["sample_ver"],
            sample_round=data["sample_round"],
            loss=data["loss"] if "loss" in data else None,
            probe_loss=(
                data["probe_loss"] if "probe_loss" in data else None
            ),
            wipe=data["wipe"] if "wipe" in data else None,
        )


# -- sparse-engine resume snapshots -------------------------------------------


def save_sparse_resume(path: str, resume: dict) -> None:
    """Persist a sim.sparse_engine resume point (device trees + host
    planner) — the sparse plane's checkpoint/resume analogue."""
    tree = (resume["sstate"], resume["swim"], resume["vis_round"])
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    arrays = {
        f"leaf{idx}": np.asarray(leaf)
        for idx, (_, leaf) in enumerate(leaves_with_paths)
    }
    arrays["__paths__"] = np.array(json.dumps(_paths(tree)).encode())
    for k, v in resume["planner"].items():
        arrays[f"planner_{k}"] = np.asarray(v)
    arrays["next_epoch"] = np.asarray(int(resume["next_epoch"]))
    np.savez_compressed(path, **arrays)


def load_sparse_resume(path: str, cfg, n_samples: int) -> dict:
    """Load a resume point for the given SparseClusterConfig; structure
    and shapes are checked against the config like load_state."""
    from corrosion_tpu.ops import sparse_writers as sw_ops
    from corrosion_tpu.ops import swim as swim_ops

    template = (
        sw_ops.init_sparse(cfg.gossip, cfg.sparse),
        swim_ops.impl(cfg.swim).init_state(cfg.swim),
        np.zeros((n_samples, cfg.n_nodes), np.int32),
    )
    with np.load(path) as data:
        saved_paths = json.loads(bytes(data["__paths__"].item()).decode())
        tmpl_paths = _paths(template)
        if saved_paths != tmpl_paths:
            raise ValueError(
                "sparse resume structure does not match the config "
                f"(saved {len(saved_paths)} leaves, config implies "
                f"{len(tmpl_paths)})"
            )
        leaves = []
        for idx, (tmpl_leaf, p) in enumerate(
            zip(jax.tree.leaves(template), tmpl_paths)
        ):
            arr = data[f"leaf{idx}"]
            if arr.shape != np.asarray(tmpl_leaf).shape:
                raise ValueError(
                    f"sparse resume leaf {p} has shape {arr.shape}, "
                    f"config implies {np.asarray(tmpl_leaf).shape}"
                )
            leaves.append(arr)
        treedef = jax.tree.structure(template)
        sstate, swim_state, vis_round = jax.tree.unflatten(treedef, leaves)
        planner = {
            k[len("planner_"):]: data[k]
            for k in data.files if k.startswith("planner_")
        }
        return {
            "sstate": sstate,
            "swim": swim_state,
            "vis_round": vis_round,
            "planner": planner,
            "next_epoch": int(data["next_epoch"]),
        }
