"""Simulation checkpoint / resume + schedule (trace) persistence.

The reference checkpoints by construction — all replication state lives in
SQLite and rehydrates at boot (SURVEY.md §5, agent.rs:147-268). The sim
analogue: a ClusterState snapshot plus the scripted Schedule IS a
replayable trace. `simulate(state=...)` already chains runs and folds the
absolute round index into each round's RNG key, so a save/resume sequence
is bit-identical to an uninterrupted run (asserted in tests).

Format: one .npz per snapshot — flat leaves keyed by pytree path, plus the
structure fingerprint so loading against a mismatched config fails loudly
instead of mis-zipping arrays.

Checkpoints are self-describing (``corro-checkpoint/1``): every save
embeds a JSON header with the schema version, checkpoint kind, config
fingerprint (``sim.benchlib.config_fingerprint``), device-mesh dims at
save time, and the absolute round index. Loaders refuse a mismatched
fingerprint up front instead of failing deep in an engine; the mesh dims
are advisory (gathered host state reshards onto any mesh — that is the
elastic plane's whole point) but let tooling report where a checkpoint
came from.
"""

from __future__ import annotations

import json

import jax
import numpy as np

from corrosion_tpu.sim.engine import ClusterState, Schedule, init_cluster

CHECKPOINT_SCHEMA = "corro-checkpoint/1"

# Fault axes save_schedule persists and sparse resume points now carry
# (the resume asymmetry fix): a resumed run must replay its fault plan.
FAULT_AXES = ("kill", "revive", "partition", "loss", "probe_loss", "wipe")


def _paths(tree) -> list[str]:
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [jax.tree_util.keystr(path) for path, _ in flat]


def _header_array(kind: str, fingerprint: str, mesh_shape, round_index):
    header = {
        "schema": CHECKPOINT_SCHEMA,
        "kind": kind,
        "config_fingerprint": str(fingerprint),
        "mesh": [int(d) for d in tuple(mesh_shape or ())],
        "round": int(round_index),
    }
    return np.array(json.dumps(header, sort_keys=True).encode())


def read_header(path: str) -> dict | None:
    """The ``corro-checkpoint/1`` header of a snapshot, or ``None`` for
    pre-header (v0) checkpoints."""
    with np.load(path) as data:
        if "__header__" not in data.files:
            return None
        return json.loads(bytes(data["__header__"].item()).decode())


def _check_header(
    path: str, data, kind: str, expect_fingerprint: str | None
) -> None:
    """Refuse a load whose header disagrees with what the caller expects.
    ``expect_fingerprint=None`` skips the fingerprint check (legacy
    callers); a checkpoint without any header passes only when no
    fingerprint is demanded."""
    if "__header__" not in data.files:
        if expect_fingerprint is not None:
            raise ValueError(
                f"{path}: checkpoint has no {CHECKPOINT_SCHEMA} header, "
                "cannot verify the config fingerprint "
                f"{expect_fingerprint!r}; re-save it or pass "
                "expect_fingerprint=None"
            )
        return
    header = json.loads(bytes(data["__header__"].item()).decode())
    if header.get("schema") != CHECKPOINT_SCHEMA:
        raise ValueError(
            f"{path}: unknown checkpoint schema {header.get('schema')!r} "
            f"(this build reads {CHECKPOINT_SCHEMA})"
        )
    if header.get("kind") != kind:
        raise ValueError(
            f"{path}: checkpoint kind {header.get('kind')!r} is not "
            f"{kind!r} — wrong loader for this file"
        )
    if (
        expect_fingerprint is not None
        and header.get("config_fingerprint") != expect_fingerprint
    ):
        raise ValueError(
            f"{path}: checkpoint config fingerprint "
            f"{header.get('config_fingerprint')!r} does not match the "
            f"running config {expect_fingerprint!r}; refusing to load "
            "state from a different configuration"
        )


def save_state(
    path: str,
    state: ClusterState,
    *,
    fingerprint: str = "",
    mesh_shape=(),
) -> None:
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(state)[0]
    arrays = {
        f"leaf{idx}": np.asarray(leaf)
        for idx, (_, leaf) in enumerate(leaves_with_paths)
    }
    arrays["__paths__"] = np.array(
        json.dumps(_paths(state)).encode()
    )
    arrays["__header__"] = _header_array(
        "state", fingerprint, mesh_shape, int(np.asarray(state.round))
    )
    np.savez_compressed(path, **arrays)


def load_state(
    path: str, cfg, n_samples: int, *, expect_fingerprint: str | None = None
) -> ClusterState:
    """Load a snapshot written by ``save_state``; ``cfg``/``n_samples``
    must describe the same cluster (shape + kernel selection). Pass the
    config's ``benchlib.config_fingerprint`` as ``expect_fingerprint``
    to refuse checkpoints from a different configuration up front."""
    with np.load(path) as data:
        _check_header(path, data, "state", expect_fingerprint)
        saved_paths = json.loads(bytes(data["__paths__"].item()).decode())
        template = init_cluster(cfg, n_samples)
        tmpl_paths = _paths(template)
        if saved_paths != tmpl_paths:
            raise ValueError(
                "checkpoint structure does not match the config "
                f"(saved {len(saved_paths)} leaves, config implies "
                f"{len(tmpl_paths)}); was it written with a different "
                "SwimConfig/GossipConfig?"
            )
        leaves = []
        for idx, (tmpl_leaf, p) in enumerate(
            zip(jax.tree.leaves(template), tmpl_paths)
        ):
            arr = data[f"leaf{idx}"]
            if arr.shape != tmpl_leaf.shape:
                raise ValueError(
                    f"checkpoint leaf {p} has shape {arr.shape}, "
                    f"config implies {tmpl_leaf.shape}"
                )
            if arr.dtype != tmpl_leaf.dtype:
                raise ValueError(
                    f"checkpoint leaf {p} has dtype {arr.dtype}, "
                    f"config implies {tmpl_leaf.dtype}"
                )
            leaves.append(arr)
        treedef = jax.tree.structure(template)
        return jax.tree.unflatten(treedef, leaves)


def save_schedule(
    path: str, schedule: Schedule, *, fingerprint: str = ""
) -> None:
    arrays = {"writes": schedule.writes}
    # Chaos axes (loss/probe_loss/wipe, sim/faults.py) persist alongside
    # the churn/partition masks: a resumed run replays its fault plan.
    for name in FAULT_AXES:
        v = getattr(schedule, name)
        if v is not None:
            arrays[name] = v
    arrays["sample_writer"] = schedule.sample_writer
    arrays["sample_ver"] = schedule.sample_ver
    arrays["sample_round"] = schedule.sample_round
    arrays["__header__"] = _header_array(
        "schedule", fingerprint, (), schedule.rounds
    )
    np.savez_compressed(path, **arrays)


def load_schedule(
    path: str, *, expect_fingerprint: str | None = None
) -> Schedule:
    with np.load(path) as data:
        _check_header(path, data, "schedule", expect_fingerprint)
        return Schedule(
            writes=data["writes"],
            kill=data["kill"] if "kill" in data else None,
            revive=data["revive"] if "revive" in data else None,
            partition=data["partition"] if "partition" in data else None,
            sample_writer=data["sample_writer"],
            sample_ver=data["sample_ver"],
            sample_round=data["sample_round"],
            loss=data["loss"] if "loss" in data else None,
            probe_loss=(
                data["probe_loss"] if "probe_loss" in data else None
            ),
            wipe=data["wipe"] if "wipe" in data else None,
        )


# -- generic pytree snapshots -------------------------------------------------


def save_tree(
    path: str,
    tree,
    *,
    fingerprint: str = "",
    mesh_shape=(),
    round_index: int = 0,
) -> None:
    """Persist an arbitrary state pytree (chunk coverage, MixedState, …)
    with the self-describing header — the elastic plane's checkpoint
    form for engines without a dedicated snapshot format."""
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    arrays = {
        f"leaf{idx}": np.asarray(leaf)
        for idx, (_, leaf) in enumerate(leaves_with_paths)
    }
    arrays["__paths__"] = np.array(json.dumps(_paths(tree)).encode())
    arrays["__header__"] = _header_array(
        "tree", fingerprint, mesh_shape, round_index
    )
    np.savez_compressed(path, **arrays)


def load_tree(path: str, template, *, expect_fingerprint: str | None = None):
    """Load a ``save_tree`` snapshot against a structure/shape/dtype
    template pytree (typically a freshly-initialized state)."""
    with np.load(path) as data:
        _check_header(path, data, "tree", expect_fingerprint)
        saved_paths = json.loads(bytes(data["__paths__"].item()).decode())
        tmpl_paths = _paths(template)
        if saved_paths != tmpl_paths:
            raise ValueError(
                "tree checkpoint structure does not match the template "
                f"(saved {len(saved_paths)} leaves, template implies "
                f"{len(tmpl_paths)})"
            )
        leaves = []
        for idx, (tmpl_leaf, p) in enumerate(
            zip(jax.tree.leaves(template), tmpl_paths)
        ):
            arr = data[f"leaf{idx}"]
            tmpl_np = np.asarray(tmpl_leaf)
            if arr.shape != tmpl_np.shape:
                raise ValueError(
                    f"tree checkpoint leaf {p} has shape {arr.shape}, "
                    f"template implies {tmpl_np.shape}"
                )
            if arr.dtype != tmpl_np.dtype:
                raise ValueError(
                    f"tree checkpoint leaf {p} has dtype {arr.dtype}, "
                    f"template implies {tmpl_np.dtype}"
                )
            leaves.append(arr)
        return jax.tree.unflatten(jax.tree.structure(template), leaves)


# -- sparse-engine resume snapshots -------------------------------------------


def save_sparse_resume(
    path: str,
    resume: dict,
    schedule: Schedule | None = None,
    *,
    fingerprint: str = "",
    mesh_shape=(),
) -> None:
    """Persist a sim.sparse_engine resume point (device trees + host
    planner) — the sparse plane's checkpoint/resume analogue.

    Pass the run's ``schedule`` to also persist its fault axes
    (kill/revive/partition/loss/probe_loss — everything ``save_schedule``
    keeps). The sparse resume protocol replays the FULL original
    schedule from ``next_epoch`` onward, so a resume point that drops
    the fault plan silently resumes fault-free; ``load_sparse_resume``
    returns the axes under ``"faults"`` and
    :func:`attach_resume_faults` re-attaches them to the rebuilt
    schedule."""
    tree = (resume["sstate"], resume["swim"], resume["vis_round"])
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    arrays = {
        f"leaf{idx}": np.asarray(leaf)
        for idx, (_, leaf) in enumerate(leaves_with_paths)
    }
    arrays["__paths__"] = np.array(json.dumps(_paths(tree)).encode())
    for k, v in resume["planner"].items():
        arrays[f"planner_{k}"] = np.asarray(v)
    arrays["next_epoch"] = np.asarray(int(resume["next_epoch"]))
    if schedule is not None:
        for name in FAULT_AXES:
            v = getattr(schedule, name)
            if v is not None:
                arrays[f"fault_{name}"] = v
    arrays["__header__"] = _header_array(
        "sparse-resume", fingerprint, mesh_shape,
        int(resume["next_epoch"]),
    )
    np.savez_compressed(path, **arrays)


def load_sparse_resume(
    path: str, cfg, n_samples: int, *, expect_fingerprint: str | None = None
) -> dict:
    """Load a resume point for the given SparseClusterConfig; structure
    and shapes are checked against the config like load_state. The
    returned dict carries any persisted fault axes under ``"faults"``
    (empty dict when the run was fault-free)."""
    from corrosion_tpu.ops import sparse_writers as sw_ops
    from corrosion_tpu.ops import swim as swim_ops

    template = (
        sw_ops.init_sparse(cfg.gossip, cfg.sparse),
        swim_ops.impl(cfg.swim).init_state(cfg.swim),
        np.zeros((n_samples, cfg.n_nodes), np.int32),
    )
    with np.load(path) as data:
        _check_header(path, data, "sparse-resume", expect_fingerprint)
        saved_paths = json.loads(bytes(data["__paths__"].item()).decode())
        tmpl_paths = _paths(template)
        if saved_paths != tmpl_paths:
            raise ValueError(
                "sparse resume structure does not match the config "
                f"(saved {len(saved_paths)} leaves, config implies "
                f"{len(tmpl_paths)})"
            )
        leaves = []
        for idx, (tmpl_leaf, p) in enumerate(
            zip(jax.tree.leaves(template), tmpl_paths)
        ):
            arr = data[f"leaf{idx}"]
            if arr.shape != np.asarray(tmpl_leaf).shape:
                raise ValueError(
                    f"sparse resume leaf {p} has shape {arr.shape}, "
                    f"config implies {np.asarray(tmpl_leaf).shape}"
                )
            leaves.append(arr)
        treedef = jax.tree.structure(template)
        sstate, swim_state, vis_round = jax.tree.unflatten(treedef, leaves)
        planner = {
            k[len("planner_"):]: data[k]
            for k in data.files if k.startswith("planner_")
        }
        faults = {
            k[len("fault_"):]: data[k]
            for k in data.files if k.startswith("fault_")
        }
        return {
            "sstate": sstate,
            "swim": swim_state,
            "vis_round": vis_round,
            "planner": planner,
            "next_epoch": int(data["next_epoch"]),
            "faults": faults,
        }


def attach_resume_faults(schedule: Schedule, resume: dict) -> Schedule:
    """Re-attach the fault axes persisted by ``save_sparse_resume`` to a
    schedule rebuilt at resume time, so the resumed run replays the same
    plan the original was under. Refuses to silently override: the
    rebuilt schedule must not already carry a conflicting axis."""
    import dataclasses

    faults = resume.get("faults", {})
    if not faults:
        return schedule
    updates = {}
    for name, arr in faults.items():
        if name not in FAULT_AXES:
            raise ValueError(f"unknown persisted fault axis {name!r}")
        existing = getattr(schedule, name)
        if existing is not None and not np.array_equal(existing, arr):
            raise ValueError(
                f"schedule already carries a different {name!r} axis; "
                "refusing to overwrite it with the checkpoint's"
            )
        if arr.shape[0] != schedule.rounds:
            raise ValueError(
                f"persisted {name!r} axis covers {arr.shape[0]} rounds, "
                f"schedule has {schedule.rounds}"
            )
        updates[name] = arr
    return dataclasses.replace(schedule, **updates)
