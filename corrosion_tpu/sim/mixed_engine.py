"""Mixed workload engine: multi-chunk transactions + version-granular
writes coexisting in ONE cluster round.

VERDICT r4 missing #2 / next-round #4. The reference's ingest pipeline
handles multi-chunk partial versions inline with normal traffic
(corro-agent/src/agent.rs:2063-2151, 1667-1806): a large transaction's
chunks buffer with gap tracking while smaller writes keep flowing, and a
version applies (watermark advance) only once gap-free. Here the two
kernel planes compose the same way:

- ``S`` large streams, each one (writer, version) pair whose CONTENT
  disseminates seq-granularly on the chunk plane (ops/chunks.py: chunk
  gossip + SyncNeedV1::Partial sync). The version number occupies a slot
  in the writer's ordinary version sequence but is never enqueued on the
  version-plane broadcast queues — its payload is far beyond the
  datagram budget; the chunk plane IS its broadcast.
- The version plane (ops/gossip.py) carries everything else. A node's
  watermark crosses the big version only when either
  (a) the chunk plane reports it fully reassembled there — the
      process_fully_buffered_changes trigger (agent.rs:1667-1806) — and
      the round's admission step then promotes contig / sets the
      possession window bit and merges the version's CRDT cells; or
  (b) anti-entropy granted it whole (the reference's sync serves
      buffered partials too, sync.rs:248-266) — the crossing is detected
      after sync_round and the node's chunk coverage is back-filled to
      complete.

Both planes advance in the same composite round, so a background write
storm and 16 large transactions genuinely share queues, sync budgets,
and convergence checks.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.ops import chunks as chunk_ops
from corrosion_tpu.ops import faulting
from corrosion_tpu.ops import gossip as gossip_ops
from corrosion_tpu.ops import intervals, swim as swim_ops
from corrosion_tpu.ops.chunks import ChunkConfig, ChunkState
from corrosion_tpu.ops.gossip import DataState, Topology
from corrosion_tpu.sim import telemetry as telemetry_mod
from corrosion_tpu.sim.engine import ClusterConfig, Schedule
from corrosion_tpu.sim.telemetry import KernelTelemetry


@dataclass(frozen=True)
class StreamSpec:
    """The large transactions: stream s is version ``version[s]`` of
    writer ``writer[s]``, committed at ``commit_round[s]`` with
    ``last_seq[s]+1`` seqs of content."""

    writer: np.ndarray  # i32[S] writer column
    version: np.ndarray  # u32[S]
    commit_round: np.ndarray  # i32[S]
    last_seq: np.ndarray  # i32[S]


class MixedState(NamedTuple):
    data: DataState
    swim: NamedTuple
    chunks: ChunkState
    applied_before: jax.Array  # bool[N, S] chunk-complete as of last round
    round: jax.Array
    vis_round: jax.Array  # i32[Samples, N]


def _admit_big(
    data: DataState,
    newly: jax.Array,  # bool[N, S] completed this round (chunk plane)
    s_writer: jax.Array,  # i32[S]
    s_version: jax.Array,  # u32[S]
    cfg,
) -> DataState:
    """Version-plane admission of newly reassembled big versions: per
    stream, rows whose watermark sits just below promote (plus window
    coalesce); rows further back set the possession window bit; rows
    beyond the window stay seen-only (sync heals them later — safe
    under-claim). Cells merge for every newly possessing row."""
    contig, oo, seen = data.contig, data.oo, data.seen
    n = contig.shape[0]
    wk = cfg.window_k
    s_count = s_writer.shape[0]
    cells = data.cells
    n_merges = jnp.uint32(0)
    for s in range(s_count):
        w = s_writer[s]
        v = s_version[s]
        col = contig[:, w]  # u32[N]
        new_s = newly[:, s]
        adv = (new_s & (col + 1 == v)).astype(jnp.int32)  # direct promote
        d_rel = v - col - 1  # window bit position (wraps when col >= v)
        in_win = new_s & (col + 1 < v) & (v <= col + jnp.uint32(wk) + 1)
        if wk:
            oo_col = oo[:, :, w]  # u32[B, N]
            bits = []
            for b in range(oo.shape[0]):
                sh = jnp.minimum(
                    d_rel - jnp.uint32(32 * b), jnp.uint32(31)
                )
                inb = in_win & (d_rel >= 32 * b) & (d_rel < 32 * (b + 1))
                bits.append(
                    jnp.where(inb, jnp.uint32(1) << sh, jnp.uint32(0))
                )
            col2, oo2 = gossip_ops.window_absorb(
                col, oo_col, adv, jnp.stack(bits)
            )
            oo = oo.at[:, :, w].set(oo2)
        else:
            col2 = col + adv.astype(jnp.uint32)
        contig = contig.at[:, w].set(col2)
        seen = seen.at[:, w].max(jnp.where(new_s, v, 0))
        if cfg.n_cells > 0:
            cells, m = gossip_ops._merge_versions_dense(
                cells, None,
                jnp.broadcast_to(w, (n, 1)),
                jnp.broadcast_to(v, (n, 1)),
                new_s[:, None], None, n, cfg,
            )
            n_merges += m
    oo_any = (data.oo_any | jnp.any(oo)) if wk else data.oo_any
    return (
        data._replace(
            contig=contig, seen=seen, oo=oo, oo_any=oo_any, cells=cells
        ),
        n_merges,
    )


def _backfill_coverage(
    chunks: ChunkState,
    crossed: jax.Array,  # bool[N, S] version-plane crossed the big version
    s_last: jax.Array,  # i32[S]
    cfg: ChunkConfig,
) -> ChunkState:
    """Anti-entropy granted the whole version: the node now holds all its
    content, so its seq coverage becomes [0, last_seq]."""
    rows = cfg.rows
    row_stream = jnp.arange(rows) % cfg.n_streams
    mask = crossed.reshape(rows)
    starts = jnp.where(
        mask[:, None],
        jnp.where(
            jnp.arange(cfg.cap)[None, :] == 0, 0, intervals.EMPTY
        ),
        chunks.have.starts,
    )
    ends = jnp.where(
        mask[:, None],
        jnp.where(
            jnp.arange(cfg.cap)[None, :] == 0,
            s_last[row_stream][:, None],
            intervals.EMPTY - 1,
        ),
        chunks.have.ends,
    )
    return ChunkState(
        have=intervals.IntervalSet(starts=starts, ends=ends)
    )


@partial(jax.jit, static_argnames=("cfg", "ccfg", "has_churn", "bcast_fn"))
def mixed_round(
    state: MixedState,
    topo: Topology,
    writes: jax.Array,  # u32[W] SMALL writes per writer this round
    big_commit: jax.Array,  # bool[S] streams committing this round
    part: jax.Array,  # bool[R, R] directional region link cuts
    kill: jax.Array,  # bool[N] (ignored when has_churn=False)
    revive: jax.Array,
    s_writer: jax.Array,  # i32[S]
    s_version: jax.Array,  # u32[S]
    s_last: jax.Array,  # i32[S]
    sample_writer: jax.Array,
    sample_ver: jax.Array,
    sample_round: jax.Array,
    rng: jax.Array,
    cfg: ClusterConfig,
    ccfg: ChunkConfig,
    has_churn: bool = False,
    loss: jax.Array | None = None,  # f32[R] chaos receiver-region loss
    probe_loss: jax.Array | None = None,  # f32[]
    wipe: jax.Array | None = None,  # bool[N] crash-with-state-wipe
    bcast_fn=None,  # static broadcast override (parallel/shard_driver)
) -> tuple[MixedState, dict]:
    # Churn/rejoin keys exist only for churn configs so fault-free runs
    # keep bit-identical RNG streams (same discipline as the dense
    # engine's cluster_round).
    if has_churn:
        k_churn, k_b, k_sw, k_sy, k_ck, k_rejoin = jax.random.split(rng, 6)
    else:
        k_b, k_sw, k_sy, k_ck = jax.random.split(rng, 4)
        k_rejoin = None
    swim_impl = swim_ops.impl(cfg.swim)
    sw = state.swim
    data = state.data
    chunks_pre = state.chunks
    applied_before = state.applied_before
    if wipe is not None:
        if not has_churn:
            raise ValueError("wipe masks require a churn schedule")
        # Crash-with-state-wipe on BOTH planes: replica state and the
        # partial-version buffers restart empty, and the completion
        # latch resets so the rebuilt coverage re-admits the big
        # versions through the normal path.
        data = faulting.wipe_nodes(data, wipe, cfg.gossip)
        chunks_pre = chunk_ops.wipe_coverage(chunks_pre, wipe, ccfg)
        applied_before = applied_before & ~wipe[:, None]
    if has_churn:
        sw = swim_impl.apply_churn(
            sw, kill, revive, k_churn, cfg.swim.max_transmissions,
            wipe=wipe,
        )
    inc_pre = sw.incarnation
    alive = sw.alive

    # Big-version commit: head/contig/seen bump at the writer WITHOUT a
    # broadcast-queue entry (the chunk plane carries the content; the
    # writer's own coverage starts full via init_chunks). Writer-side
    # cells merge here (the local txn materialization).
    # The writer's own cells for the big version merge through the
    # admission path: its chunk coverage is full from commit, so `newly`
    # includes the writer row on commit round.
    def commit_one(data, s):
        w = s_writer[s]
        wnode = topo.writer_nodes[w]
        v = s_version[s]
        on = big_commit[s]
        head = data.head.at[w].max(jnp.where(on, v, 0))
        contig = data.contig.at[wnode, w].max(jnp.where(on, v, 0))
        seen = data.seen.at[wnode, w].max(jnp.where(on, v, 0))
        return data._replace(head=head, contig=contig, seen=seen)

    for s in range(s_writer.shape[0]):
        data = commit_one(data, s)

    # Chunk plane round (content dissemination + partial-need sync). The
    # chunk plane has no region structure, so a regional loss schedule
    # degrades to its worst-region scalar here.
    chunks, cstats = chunk_ops.chunk_round(
        chunks_pre, s_last, alive, state.round, k_ck, ccfg,
        loss=None if loss is None else jnp.max(loss),
    )
    applied_now = chunk_ops.applied_mask(chunks, s_last, ccfg)  # [N, S]
    committed = big_commit | (
        data.head[jnp.maximum(s_writer, 0)] >= s_version
    )
    applied_now = applied_now & committed[None, :]
    newly = applied_now & ~applied_before

    # Version-plane admission of freshly reassembled big versions.
    data, admit_merges = _admit_big(
        data, newly, s_writer, s_version, cfg.gossip
    )

    # Ordinary broadcast + SWIM + sync. The broadcast plane's driver is
    # pluggable exactly like the dense engine: ``bcast_fn`` (trace-time
    # static) swaps in the explicit shard_map delivery of
    # parallel/shard_driver.make_sharded_broadcast.
    bfn = gossip_ops.broadcast_round if bcast_fn is None else bcast_fn
    data, bstats = bfn(
        data, topo, alive, part, writes, k_b, cfg.gossip, loss=loss
    )
    sw = swim_impl.swim_round(
        sw, k_sw, state.round, cfg.swim, probe_loss=probe_loss
    )
    contig_pre = data.contig
    data, sstats = gossip_ops.sync_round(
        data, topo, alive, part, state.round, k_sy, cfg.gossip
    )
    if has_churn:
        # Rejoining nodes pull immediately instead of waiting out their
        # cohort slot (the reference syncs on rejoin) — same semantics
        # as the dense engine; wiped rejoiners bootstrap from empty.
        data, rstats = gossip_ops.revive_sync(
            data, topo, alive, part, revive, k_rejoin, cfg.gossip
        )
        sstats = {k: sstats[k] + rstats[k] for k in sstats}
    # Sync crossings: nodes granted the whole big version back-fill their
    # chunk coverage (the content came through the sync stream).
    crossed = (
        (contig_pre[:, jnp.maximum(s_writer, 0)] < s_version[None, :])
        & (data.contig[:, jnp.maximum(s_writer, 0)] >= s_version[None, :])
    )
    chunks = _backfill_coverage(chunks, crossed, s_last, ccfg)
    applied_after = (
        chunk_ops.applied_mask(chunks, s_last, ccfg) & committed[None, :]
    )

    # Visibility over sampled SMALL writes and big versions alike rides
    # the version plane (possession = watermark or window).
    vis_now = gossip_ops.visibility(
        data, sample_writer, sample_ver,
        backend=cfg.gossip.kernel_backend,
    )
    active = state.round >= sample_round
    vis_round = jnp.where(
        (state.vis_round < 0) & vis_now & active[:, None],
        state.round,
        state.vis_round,
    )
    newly = (vis_round >= 0) & (state.vis_round < 0)

    # Canonical RoundCurves schema (sim/telemetry.py): version-plane
    # traffic rides the usual keys, the chunk plane stays separable via
    # ``chunks_sent`` / ``seqs_granted`` / ``streams_applied`` (the big
    # transactions' completion level), and the convergence health keys
    # measure the composite: staleness over the version-plane watermarks
    # (the big versions count — a node lags until its watermark crosses
    # them), `need` carries both planes' outstanding mass.
    stale_sum, stale_max = gossip_ops.staleness(data)
    false_alarms, undetected = swim_impl.health_counts(sw)
    # Propagation plane over the version-plane broadcast traffic (the
    # chunk plane has no region structure; its copies are excluded from
    # the link matrix by construction). Rumor ages ride the composite
    # visibility latch, so a big version first delivered through chunk
    # reassembly ages like any other first delivery. Static skip when
    # cfg.gossip.prop_observe is off.
    prop_stats = telemetry_mod.prop_curves(
        cfg.gossip.prop_observe,
        bstats.get("prop_link"),
        bstats.get("prop_useful"),
        bstats.get("prop_dup"),
        state.round - sample_round[:, None],
        newly,
        kills=bstats.get("prop_kills"),
        pulls=bstats.get("prop_pulls"),
    )
    stats = telemetry_mod.round_curves(
        msgs=bstats["msgs"],
        applied_broadcast=bstats["applied_broadcast"],
        applied_sync=sstats["applied_sync"],
        cell_merges=(
            bstats["cell_merges"] + sstats["cell_merges"] + admit_merges
        ),
        sessions=sstats["sessions"],
        mismatches=swim_impl.mismatches(sw),
        chunks_sent=cstats["chunks_sent"],
        seqs_granted=cstats["seqs_granted"],
        streams_applied=jnp.sum(applied_after, dtype=jnp.uint32),
        need=(
            gossip_ops.total_need(data).astype(jnp.float32)
            + cstats["need_seqs"]
        ),
        window_degraded=bstats["window_degraded"],
        sync_regrant=sstats["sync_regrant"],
        vis_count=jnp.sum(newly, dtype=jnp.uint32),
        staleness_sum=stale_sum,
        staleness_max=stale_max,
        swim_false_alarms=false_alarms,
        swim_undetected_deaths=undetected,
        swim_flaps=jnp.sum(sw.incarnation != inc_pre, dtype=jnp.uint32),
        queue_backlog=gossip_ops.queue_backlog(data),
        chaos_lost_msgs=bstats["lost_msgs"] + cstats["lost_msgs"],
        chaos_wiped=(
            jnp.uint32(0) if wipe is None
            else jnp.sum(wipe, dtype=jnp.uint32)
        ),
        # Cross-shard traffic of the explicit exchange (zero under the
        # single-host/GSPMD drivers; see sim/engine.py).
        xshard_bytes_ici=bstats.get("xshard_bytes_ici", jnp.float32(0.0)),
        xshard_bytes_dcn=bstats.get("xshard_bytes_dcn", jnp.float32(0.0)),
        **telemetry_mod.delivery_latency_hist(
            state.round - sample_round[:, None], newly
        ),
        **prop_stats,
    )
    return (
        MixedState(
            data=data, swim=sw, chunks=chunks,
            applied_before=applied_after,
            round=state.round + 1, vis_round=vis_round,
        ),
        stats,
    )


def _scan_mixed_impl(
    state, topo, xs, s_writer, s_version, s_last, s_w, s_v, s_r,
    base_key, cfg, ccfg, has_churn, bcast_fn=None,
):
    """Whole-chunk scan, jitted once per (cfg, shapes) — chunked runs
    with equal chunk lengths hit the compile cache."""

    def body(carry, x):
        w, c, p, kl, rv, r, lo, pl, wp = x
        key = jax.random.fold_in(base_key, r)
        return mixed_round(
            carry, topo, w, c, p, kl, rv, s_writer, s_version, s_last,
            s_w, s_v, s_r, key, cfg, ccfg, has_churn,
            loss=lo, probe_loss=pl, wipe=wp, bcast_fn=bcast_fn,
        )

    return jax.lax.scan(body, state, xs)


# Donated twin: the carried MixedState aliases into the output so chunked
# runs round-trip the data+swim+chunk-coverage buffers in place. It is
# the driver's only scan entry (a second non-donating compile would
# double the first chunk's dominant cost); the first chunk's
# freshly-built carry is made donatable by one deep copy — zero-filled
# leaves can share one constant buffer, which XLA rejects as a double
# donation. The plain entry remains for ad-hoc callers.
_scan_mixed = partial(
    jax.jit, static_argnames=("cfg", "ccfg", "has_churn", "bcast_fn")
)(_scan_mixed_impl)
_scan_mixed_donated = partial(
    jax.jit, static_argnames=("cfg", "ccfg", "has_churn", "bcast_fn"),
    donate_argnums=(0,),
)(_scan_mixed_impl)


def init_mixed_state(
    cfg: ClusterConfig,
    ccfg: ChunkConfig,
    topo: Topology,
    schedule: Schedule,
    streams: StreamSpec,
) -> MixedState:
    """Fresh composite state for ``simulate_mixed`` — factored out so the
    sharded driver (parallel/shard_driver.py) can build it, place it on a
    mesh, and pass it back through ``simulate_mixed(state=...)``."""
    n = cfg.n_nodes
    s_last = jnp.asarray(streams.last_seq, jnp.int32)
    origin_nodes = np.asarray(topo.writer_nodes)[
        np.asarray(streams.writer)
    ]
    return MixedState(
        data=gossip_ops.init_data(cfg.gossip),
        swim=swim_ops.impl(cfg.swim).init_state(cfg.swim),
        chunks=chunk_ops.init_chunks(
            ccfg, jnp.asarray(origin_nodes, jnp.int32), s_last
        ),
        applied_before=jnp.zeros((n, len(streams.writer)), bool),
        round=jnp.int32(0),
        vis_round=jnp.full(
            (len(schedule.sample_writer), n), -1, jnp.int32
        ),
    )


def simulate_mixed(
    cfg: ClusterConfig,
    ccfg: ChunkConfig,
    topo: Topology,
    schedule: Schedule,  # SMALL writes only
    streams: StreamSpec,
    seed: int = 0,
    max_chunk: int | None = None,
    telemetry: KernelTelemetry | None = None,
    state: MixedState | None = None,
    bcast_fn=None,
):
    """Scan mixed_round over the schedule. Returns (final, curves).

    Emits the canonical RoundCurves schema (sim/telemetry.py) like every
    other engine. ``max_chunk`` splits the run into several device
    executions (state carried across; per-round RNG keys fold the
    absolute round index, so results are identical either way), and
    ``telemetry`` (sim.telemetry.KernelTelemetry) instruments each
    execution as a chunk — timed, spanned, flushed to the flight
    recorder, with run totals folded into the metrics registry.

    ``state`` supplies a pre-built (e.g. node-sharded) initial
    MixedState — ``init_mixed_state`` builds the canonical fresh one —
    and ``bcast_fn`` (trace-time static) swaps the broadcast plane's
    driver, the multi-chip path being
    ``parallel.shard_driver.make_sharded_broadcast(mesh)`` (use
    ``parallel.simulate_mixed_sharded`` for the packaged form).

    Resume seam (elastic checkpoint-reshard): a ``state`` whose carried
    ``round`` is ``k > 0`` resumes at absolute round ``k`` — pass the
    TAIL slice of the schedule/fault axes (``schedule.rounds`` = the
    remaining rounds); per-round RNG keys and the stream commit matrix
    are indexed by ``k + r``, so the resumed run is bit-identical to the
    uninterrupted one. ``streams.commit_round`` stays absolute; streams
    that committed before ``k`` already live in the carried coverage.
    """
    n = cfg.n_nodes
    s_writer = jnp.asarray(streams.writer, jnp.int32)
    s_version = jnp.asarray(streams.version, jnp.uint32)
    s_last = jnp.asarray(streams.last_seq, jnp.int32)
    if state is None:
        state = init_mixed_state(cfg, ccfg, topo, schedule, streams)
    # The carried round index anchors the resumed run in absolute
    # rounds; fresh states carry 0, keeping the uninterrupted path
    # bit-for-bit unchanged.
    offset = int(np.asarray(state.round))
    rounds = schedule.rounds
    writes = jnp.asarray(schedule.writes, jnp.uint32)
    commit = np.zeros((rounds, len(streams.writer)), bool)
    for s, r in enumerate(streams.commit_round):
        if offset <= r < offset + rounds:
            commit[r - offset, s] = True
    commit = jnp.asarray(commit)
    s_w = jnp.asarray(schedule.sample_writer)
    s_v = jnp.asarray(schedule.sample_ver)
    s_r = jnp.asarray(schedule.sample_round)
    base_key = jax.random.PRNGKey(seed)

    # Chaos axes (sim/faults.apply_plan): same dummy-mask discipline as
    # the dense engine — churn-free runs keep 1-wide placeholders and
    # the bit-identical fault-free trace.
    n_regions = topo.region_rtt.shape[0]
    has_churn = (
        schedule.kill is not None
        or schedule.revive is not None
        or schedule.wipe is not None
    )
    if has_churn:
        zeros_n = np.zeros((rounds, n), dtype=bool)
        kill = jnp.asarray(
            schedule.kill if schedule.kill is not None else zeros_n
        )
        revive = jnp.asarray(
            schedule.revive if schedule.revive is not None else zeros_n
        )
    else:
        kill = revive = jnp.zeros((rounds, 1), dtype=bool)
    if schedule.partition is not None:
        partition = jnp.asarray(schedule.partition)
    else:
        partition = jnp.zeros((rounds, n_regions, n_regions), dtype=bool)
    loss = (
        None if schedule.loss is None
        else jnp.asarray(schedule.loss, jnp.float32)
    )
    probe_loss = (
        None if schedule.probe_loss is None
        else jnp.asarray(schedule.probe_loss, jnp.float32)
    )
    wipe = None if schedule.wipe is None else jnp.asarray(schedule.wipe)

    step = max_chunk if max_chunk is not None else max(rounds, 1)
    curve_parts: list[dict] = (
        [] if rounds > 0
        else [{k: np.zeros((0,)) for k in telemetry_mod.ROUND_CURVE_KEYS}]
    )
    owned = False  # first chunk's carry needs the ownership copy
    for r0 in range(0, rounds, step):
        r1 = min(r0 + step, rounds)
        xs = (
            writes[r0:r1], commit[r0:r1], partition[r0:r1],
            kill[r0:r1], revive[r0:r1],
            jnp.arange(offset + r0, offset + r1, dtype=jnp.int32),
            None if loss is None else loss[r0:r1],
            None if probe_loss is None else probe_loss[r0:r1],
            None if wipe is None else wipe[r0:r1],
        )
        if not owned:
            state = telemetry_mod.owned_copy(state)
        if telemetry is None:
            state, curves = _scan_mixed_donated(
                state, topo, xs, s_writer, s_version, s_last,
                s_w, s_v, s_r, base_key, cfg, ccfg, has_churn,
                bcast_fn=bcast_fn,
            )
        else:
            def _run(state=state, xs=xs):
                return _scan_mixed_donated(
                    state, topo, xs, s_writer, s_version, s_last,
                    s_w, s_v, s_r, base_key, cfg, ccfg, has_churn,
                    bcast_fn=bcast_fn,
                )

            state, curves = telemetry.run_chunk(offset + r0, _run)
        owned = True
        curve_parts.append({k: np.asarray(v) for k, v in curves.items()})
    merged = {
        k: np.concatenate([p[k] for p in curve_parts])
        for k in curve_parts[0]
    }
    if telemetry is not None:
        telemetry.on_run_end(merged)
    return state, merged
