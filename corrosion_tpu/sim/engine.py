"""Whole-cluster simulation engine.

Composes the three gossip planes the reference runs as concurrent async loops
(SURVEY.md §3: SWIM runtime loop, broadcast loop, sync loop) into one
bulk-synchronous `cluster_round`, then `lax.scan`s it over a scripted
workload. The scripted-schedule shape mirrors the reference's integration
tests (SURVEY.md §4 stress_test: fire statements at agents, then poll for
cluster-wide convergence) — writes per (round, writer), churn kill/revive
masks, and region partition masks.

Round model: one round ≈ the broadcast flush tick (500 ms,
broadcast/mod.rs:373); the SWIM probe and sync cadences are expressed in
rounds (SwimConfig / GossipConfig.sync_interval). `round_ms` converts
round-count latencies into wall-clock-equivalent seconds for BASELINE
comparisons.

Change-visibility metric: sampled writes (writer, version, commit round) are
tracked to first-visibility round per node — exact p50/p99 over samples, the
reference's headline "how fast is a write visible cluster-wide" question
(README.md:12).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.ops import faulting
from corrosion_tpu.ops import gossip as gossip_ops
from corrosion_tpu.ops import swim as swim_ops
from corrosion_tpu.ops.gossip import DataState, GossipConfig, Topology
from corrosion_tpu.ops.swim import SwimConfig, SwimState
from corrosion_tpu.sim import telemetry as telemetry_mod
from corrosion_tpu.sim.telemetry import KernelTelemetry


@dataclass(frozen=True)
class ClusterConfig:
    swim: SwimConfig
    gossip: GossipConfig
    round_ms: float = 500.0  # simulated wall-clock per round

    @property
    def n_nodes(self) -> int:
        return self.gossip.n_nodes


class ClusterState(NamedTuple):
    swim: NamedTuple  # SwimState or SparseSwimState (swim_ops.impl(cfg.swim))
    data: DataState
    round: jax.Array  # i32
    vis_round: jax.Array  # i32[S, N] first round sample s visible at node, -1


@dataclass
class Schedule:
    """Scripted workload for a run of ``rounds`` rounds.

    writes: u8/u32[rounds, W] versions committed per writer per round.
    kill/revive: optional bool[rounds, N] churn masks.
    partition: optional bool[rounds, R, R] region link cuts — DIRECTIONAL:
      ``partition[t, i, j]`` True means receivers in region i cannot hear
      sources in region j at round t (a symmetric matrix gives the
      classic two-way cut; the chaos plane emits one-way cuts too).
    samples: (writer[S], version[S], round[S]) — writes whose visibility is
      tracked. ``make_samples`` derives them from ``writes``.

    Chaos-plane axes (sim/faults.apply_plan attaches them; ``None`` keeps
    the engines' static zero-cost fault-free trace):

    loss: optional f32[rounds, R] injected receiver-region loss prob.
    probe_loss: optional f32[rounds] SWIM probe/ack-only loss prob.
    wipe: optional bool[rounds, N] crash-with-state-wipe mask (applies at
      the kill round; see ops/faulting.wipe_nodes and gossip.revive_sync
      for the per-engine semantics).
    """

    writes: np.ndarray
    kill: np.ndarray | None = None
    revive: np.ndarray | None = None
    partition: np.ndarray | None = None
    sample_writer: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int32))
    sample_ver: np.ndarray = field(default_factory=lambda: np.zeros(0, np.uint32))
    sample_round: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int32))
    loss: np.ndarray | None = None
    probe_loss: np.ndarray | None = None
    wipe: np.ndarray | None = None

    @property
    def rounds(self) -> int:
        return self.writes.shape[0]

    def make_samples(self, cap: int = 256) -> "Schedule":
        """Sample up to ``cap`` committed writes, evenly over the schedule."""
        rs, ws = np.nonzero(self.writes)
        if len(rs) == 0:
            return self
        heads = np.zeros(self.writes.shape[1], np.uint32)
        trip = []  # (writer, version, round) per committed version
        for r, w in zip(rs, ws):
            n = int(self.writes[r, w])
            for j in range(n):
                heads[w] += 1
                trip.append((w, heads[w], r))
        idx = np.linspace(0, len(trip) - 1, min(cap, len(trip))).astype(int)
        sel = [trip[i] for i in idx]
        self.sample_writer = np.array([s[0] for s in sel], np.int32)
        self.sample_ver = np.array([s[1] for s in sel], np.uint32)
        self.sample_round = np.array([s[2] for s in sel], np.int32)
        return self


def init_cluster(cfg: ClusterConfig, n_samples: int) -> ClusterState:
    return ClusterState(
        swim=swim_ops.impl(cfg.swim).init_state(cfg.swim),
        data=gossip_ops.init_data(cfg.gossip),
        round=jnp.int32(0),
        vis_round=jnp.full((n_samples, cfg.n_nodes), -1, jnp.int32),
    )


def _cluster_round(
    state: ClusterState,
    topo: Topology,
    writes: jax.Array,  # u32[W]
    partition: jax.Array,  # bool[R, R]
    kill: jax.Array,  # bool[N] (ignored when has_churn=False)
    revive: jax.Array,
    sample_writer: jax.Array,  # i32[S]
    sample_ver: jax.Array,  # u32[S]
    sample_round: jax.Array,  # i32[S]
    rng: jax.Array,
    cfg: ClusterConfig,
    has_churn: bool,
    loss: jax.Array | None = None,  # f32[R] chaos receiver-region loss
    probe_loss: jax.Array | None = None,  # f32[] chaos probe/ack loss
    wipe: jax.Array | None = None,  # bool[N] crash-with-state-wipe
    bcast_fn=None,  # static broadcast override (parallel/shard_driver)
) -> tuple[ClusterState, dict]:
    # The rejoin key exists only for churn configs, so churn-free runs
    # keep bit-identical RNG streams with earlier measurements. The
    # chaos axes (loss/probe_loss/wipe) are trace-time optional the same
    # way: a fault-free plan leaves them None and this trace is the
    # pre-chaos one.
    if has_churn:
        k_churn, k_bcast, k_swim, k_sync, k_rejoin = jax.random.split(rng, 5)
    else:
        k_churn, k_bcast, k_swim, k_sync = jax.random.split(rng, 4)
        k_rejoin = None
    swim_impl = swim_ops.impl(cfg.swim)
    sw = state.swim
    data_pre = state.data
    if wipe is not None:
        if not has_churn:
            raise ValueError("wipe masks require a churn schedule")
        # Crash-with-state-wipe at the kill round: replica state resets
        # BEFORE this round's protocol work, so the restarted node
        # participates from empty like a real rejoining process.
        data_pre = faulting.wipe_nodes(data_pre, wipe, cfg.gossip)
    if has_churn:
        sw = swim_impl.apply_churn(
            sw, kill, revive, k_churn, cfg.swim.max_transmissions,
            wipe=wipe,
        )
    alive = sw.alive

    # The broadcast plane is the one round stage with a pluggable driver:
    # ``bcast_fn`` (trace-time static) swaps in the explicit shard_map
    # delivery of parallel/shard_driver.make_sharded_broadcast — same
    # signature, same stats contract plus the cross-shard byte counts.
    bfn = gossip_ops.broadcast_round if bcast_fn is None else bcast_fn
    with jax.named_scope("corro_broadcast"):
        data, bstats = bfn(
            data_pre, topo, alive, partition, writes, k_bcast, cfg.gossip,
            loss=loss,
        )
    with jax.named_scope("corro_swim"):
        # Snapshot incarnations AFTER churn (revive bumps are rejoins,
        # not flaps) so swim_flaps counts only refutation-driven bumps.
        inc_pre = sw.incarnation
        sw = swim_impl.swim_round(
            sw, k_swim, state.round, cfg.swim, probe_loss=probe_loss
        )
    with jax.named_scope("corro_sync"):
        data, sstats = gossip_ops.sync_round(
            data, topo, alive, partition, state.round, k_sync, cfg.gossip
        )
        if has_churn:
            # Rejoining nodes pull immediately instead of waiting out their
            # cohort slot (the reference syncs on rejoin).
            data, rstats = gossip_ops.revive_sync(
                data, topo, alive, partition, revive, k_rejoin, cfg.gossip
            )
            sstats = {k: sstats[k] + rstats[k] for k in sstats}

    # Visibility tracking for sampled writes that have been committed.
    with jax.named_scope("corro_track"):
        active = state.round >= sample_round  # [S]
        vis_now = gossip_ops.visibility(
            data, sample_writer, sample_ver,
            backend=cfg.gossip.kernel_backend,
        )  # [S, N]
        vis_round = jnp.where(
            (state.vis_round < 0) & vis_now & active[:, None],
            state.round,
            state.vis_round,
        )

    # Convergence health observables (all elementwise/reduce — they fuse
    # into the round; docs/OBSERVABILITY.md "Convergence plane").
    with jax.named_scope("corro_health"):
        newly = (vis_round >= 0) & (state.vis_round < 0)
        lat_hist = telemetry_mod.delivery_latency_hist(
            state.round - sample_round[:, None], newly
        )
        stale_sum, stale_max = gossip_ops.staleness(data)
        false_alarms, undetected = swim_impl.health_counts(sw)
        # Propagation plane (docs/OBSERVABILITY.md "Propagation
        # plane"): static zero-cost skip when cfg.gossip.prop_observe
        # is off — prop_curves returns {} and nothing traces.
        prop_stats = telemetry_mod.prop_curves(
            cfg.gossip.prop_observe,
            bstats.get("prop_link"),
            bstats.get("prop_useful"),
            bstats.get("prop_dup"),
            state.round - sample_round[:, None],
            newly,
            kills=bstats.get("prop_kills"),
            pulls=bstats.get("prop_pulls"),
        )

    stats = telemetry_mod.round_curves(
        mismatches=swim_impl.mismatches(sw),
        need=gossip_ops.total_need(data),
        applied_broadcast=bstats["applied_broadcast"],
        applied_sync=sstats["applied_sync"],
        msgs=bstats["msgs"],
        sessions=sstats["sessions"],
        cell_merges=bstats["cell_merges"] + sstats["cell_merges"],
        window_degraded=bstats["window_degraded"],
        sync_regrant=sstats["sync_regrant"],
        vis_count=jnp.sum(newly, dtype=jnp.uint32),
        staleness_sum=stale_sum,
        staleness_max=stale_max,
        swim_false_alarms=false_alarms,
        swim_undetected_deaths=undetected,
        swim_flaps=jnp.sum(sw.incarnation != inc_pre, dtype=jnp.uint32),
        queue_backlog=gossip_ops.queue_backlog(data),
        chaos_lost_msgs=bstats["lost_msgs"],
        chaos_wiped=(
            jnp.uint32(0) if wipe is None
            else jnp.sum(wipe, dtype=jnp.uint32)
        ),
        # Cross-shard traffic of the explicit exchange (zero under the
        # single-host/GSPMD drivers — only the shard_map broadcast
        # reports bytes, and they are exact static accounting).
        xshard_bytes_ici=bstats.get("xshard_bytes_ici", jnp.float32(0.0)),
        xshard_bytes_dcn=bstats.get("xshard_bytes_dcn", jnp.float32(0.0)),
        **lat_hist,
        **prop_stats,
    )
    return (
        ClusterState(
            swim=sw, data=data, round=state.round + 1, vis_round=vis_round
        ),
        stats,
    )


# Public entry points. ``cluster_round_donated`` aliases the carried
# ClusterState into the output (XLA reuses the round-trip buffers in
# place — the whole data+swim state, ~10 MiB at 512 nodes and ~GiB at the
# 100k configs). Donation binds at top-level calls only; the plain entry
# stays the default for ad-hoc stepping where the caller may re-read its
# input state. See docs/PERFORMANCE.md ("Donation invariants").
cluster_round = partial(
    jax.jit, static_argnames=("cfg", "has_churn", "bcast_fn")
)(_cluster_round)
cluster_round_donated = partial(
    jax.jit, static_argnames=("cfg", "has_churn", "bcast_fn"),
    donate_argnums=(0,),
)(_cluster_round)


def simulate(
    cfg: ClusterConfig,
    topo: Topology,
    schedule: Schedule,
    seed: int = 0,
    state: ClusterState | None = None,
    max_chunk: int | None = None,
    telemetry: KernelTelemetry | None = None,
    bcast_fn=None,
    _donate_state: bool = False,
) -> tuple[ClusterState, dict]:
    """Scan `cluster_round` over the schedule. Returns final state + per-round
    metric curves (numpy arrays of length schedule.rounds).

    ``max_chunk`` splits the run into several device executions of at most
    that many rounds (state carried between them): long single executions
    can trip device-side watchdogs, and chunking also bounds the stacked
    curve buffers. Results are identical either way — per-round RNG keys
    fold in the absolute round index.

    ``bcast_fn`` (trace-time static) swaps the broadcast plane's driver —
    the multi-chip path passes
    ``parallel.shard_driver.make_sharded_broadcast(mesh)`` with a
    node-sharded ``state`` and a replicated ``topo`` (use
    ``parallel.simulate_sharded`` for the packaged form).

    ``telemetry`` (sim.telemetry.KernelTelemetry) instruments the run:
    each chunk execution (the whole run counts as one chunk when
    unchunked) is timed, spanned, and flushed to the flight recorder,
    and the finished curves fold into the metrics registry as
    ``corro_kernel_*`` series. Curves and final state are unchanged.

    Buffer donation: the scan always runs through the donated entry, so
    the carried state round-trips in place; a run's first carry is made
    donatable by one deep copy (``telemetry.owned_copy``), amortized across all
    its chunks. A caller-supplied ``state`` is therefore never consumed
    — it stays readable after the call (``_donate_state`` is the
    internal recursion flag marking an already-owned carry; callers
    leave it False). Results are bit-identical with or without donation
    (tests/test_perf_plane.py pins this).
    """
    # The CRDT merge packs (cl, col_version) into one u32 (ops/crdt.py
    # apply_changes): versions must stay below 2^24. Bound the reachable
    # head conservatively at schedule-validation time so the domain is
    # enforced loudly, not by silent bit bleed.
    start_round = 0 if state is None else int(np.asarray(state.round))
    max_head = (start_round + schedule.rounds) * max(
        cfg.gossip.max_writes_per_round, 1
    )
    if cfg.gossip.n_cells > 0 and max_head >= (1 << 24):
        raise ValueError(
            f"reachable version head {max_head} exceeds the CRDT pack "
            f"domain (< 2^24); shorten the run or disable the cell plane"
        )
    if max_chunk is not None and schedule.rounds > max_chunk:
        cur = state
        # The first chunk takes ownership of the carry (one owned_copy
        # inside the recursive call unless the recursion already marked
        # it owned); every later chunk's input is the previous chunk's
        # output — owned by construction, donated without a copy.
        owned = _donate_state
        curve_parts: list[dict] = []
        for start in range(0, schedule.rounds, max_chunk):
            stop = min(start + max_chunk, schedule.rounds)
            part = Schedule(
                writes=schedule.writes[start:stop],
                kill=None if schedule.kill is None else schedule.kill[start:stop],
                revive=(
                    None if schedule.revive is None
                    else schedule.revive[start:stop]
                ),
                partition=(
                    None if schedule.partition is None
                    else schedule.partition[start:stop]
                ),
                sample_writer=schedule.sample_writer,
                sample_ver=schedule.sample_ver,
                sample_round=schedule.sample_round,
                loss=(
                    None if schedule.loss is None
                    else schedule.loss[start:stop]
                ),
                probe_loss=(
                    None if schedule.probe_loss is None
                    else schedule.probe_loss[start:stop]
                ),
                wipe=(
                    None if schedule.wipe is None
                    else schedule.wipe[start:stop]
                ),
            )
            if telemetry is None:
                cur, curves = simulate(
                    cfg, topo, part, seed=seed, state=cur,
                    bcast_fn=bcast_fn, _donate_state=owned,
                )
            else:
                # Chunk boundary: time the execution, span it, and flush
                # the chunk's per-round curves to the flight recorder so
                # long runs stream progress instead of going dark.
                cur, curves = telemetry.run_chunk(
                    start_round + start,
                    lambda part=part, cur=cur, owned=owned: simulate(
                        cfg, topo, part, seed=seed, state=cur,
                        bcast_fn=bcast_fn, _donate_state=owned,
                    ),
                )
            owned = True
            curve_parts.append(curves)
        merged = {
            k: np.concatenate([p[k] for p in curve_parts])
            for k in curve_parts[0]
        }
        if telemetry is not None:
            telemetry.on_run_end(merged)
        return cur, merged
    n = cfg.n_nodes
    n_regions = int(np.asarray(topo.region).max()) + 1
    # A wipe mask implies churn (the wipe applies at the kill round).
    has_churn = (
        schedule.kill is not None
        or schedule.revive is not None
        or schedule.wipe is not None
    )
    rounds = schedule.rounds

    writes = jnp.asarray(schedule.writes, dtype=jnp.uint32)
    if has_churn:
        zeros_n = np.zeros((rounds, n), dtype=bool)
        kill = jnp.asarray(
            schedule.kill if schedule.kill is not None else zeros_n
        )
        revive = jnp.asarray(
            schedule.revive if schedule.revive is not None else zeros_n
        )
    else:
        # Dummy 1-wide masks: cluster_round skips churn entirely, and this
        # avoids materializing rounds x N host arrays for churn-free runs.
        kill = revive = jnp.zeros((rounds, 1), dtype=bool)
    if schedule.partition is not None:
        partition = jnp.asarray(schedule.partition)
    else:
        partition = jnp.zeros((rounds, n_regions, n_regions), dtype=bool)
    # Chaos axes: None stays None (trace-time absent — the static
    # zero-cost skip all the way down to ops/faulting.apply_loss).
    loss = (
        None if schedule.loss is None
        else jnp.asarray(schedule.loss, dtype=jnp.float32)
    )
    probe_loss = (
        None if schedule.probe_loss is None
        else jnp.asarray(schedule.probe_loss, dtype=jnp.float32)
    )
    wipe = None if schedule.wipe is None else jnp.asarray(schedule.wipe)

    s_writer = jnp.asarray(schedule.sample_writer)
    s_ver = jnp.asarray(schedule.sample_ver)
    s_round = jnp.asarray(schedule.sample_round)
    if state is None:
        state = init_cluster(cfg, len(schedule.sample_writer))
        offset = 0
        owned = False
    else:
        # Continue from the carried round counter so chunked/chained runs
        # fold distinct per-round RNG keys.
        offset = int(np.asarray(state.round))
        owned = _donate_state
    if not owned:
        # One copy makes the carry donatable (see _scan_rounds_donated);
        # chunked runs pay it on the first chunk only.
        state = telemetry_mod.owned_copy(state)
    base_key = jax.random.PRNGKey(seed)

    xs = (
        writes, partition, kill, revive,
        jnp.arange(offset, offset + rounds, dtype=jnp.int32),
        loss, probe_loss, wipe,
    )
    if telemetry is None:
        final, curves = _scan_rounds_donated(
            state, topo, xs, s_writer, s_ver, s_round, base_key, cfg,
            has_churn, bcast_fn=bcast_fn,
        )
    else:
        # Unchunked run with telemetry: the whole execution is one chunk.
        final, curves = telemetry.run_chunk(
            offset,
            lambda: _scan_rounds_donated(
                state, topo, xs, s_writer, s_ver, s_round, base_key, cfg,
                has_churn, bcast_fn=bcast_fn,
            ),
        )
    curves = {k: np.asarray(v) for k, v in curves.items()}
    if telemetry is not None:
        telemetry.on_run_end(curves)
    return final, curves


def _scan_rounds_impl(
    state, topo, xs, s_writer, s_ver, s_round, base_key, cfg, has_churn,
    bcast_fn=None,
):
    """Whole-run scan, jitted once per (cfg, shapes): repeat calls — e.g. a
    timed bench run after a warm-up — hit the compile cache (the seed is a
    traced argument, not a constant)."""

    def body(carry, x):
        w, p, kl, rv, r, lo, pl, wp = x
        key = jax.random.fold_in(base_key, r)
        return cluster_round(
            carry, topo, w, p, kl, rv, s_writer, s_ver, s_round, key, cfg,
            has_churn, loss=lo, probe_loss=pl, wipe=wp, bcast_fn=bcast_fn,
        )

    return jax.lax.scan(body, state, xs)


# The donated twin is the driver's ONLY scan entry (one compiled
# executable per config — a second non-donating twin would double the
# dominant compile cost of every chunked first call): each chunk's carry
# aliases into its output, so the ~state-sized copy per chunk collapses
# to an in-place round-trip. Carries the driver does not own — a
# caller-supplied resume state (which must stay readable; checkpoint
# flows and tests re-read it) or a freshly-built init (identical
# zero-filled leaves can share one constant buffer, which XLA rejects as
# a double donation) — are made owned by ONE `telemetry.owned_copy` per run,
# amortized across all chunks. The plain entry remains for ad-hoc
# callers that want non-consuming semantics without a copy.
_scan_rounds = partial(
    jax.jit, static_argnames=("cfg", "has_churn", "bcast_fn")
)(_scan_rounds_impl)
_scan_rounds_donated = partial(
    jax.jit, static_argnames=("cfg", "has_churn", "bcast_fn"),
    donate_argnums=(0,),
)(_scan_rounds_impl)


def visibility_latencies(
    final: ClusterState, schedule: Schedule, cfg: ClusterConfig,
    alive_only: bool = True,
) -> dict:
    """p50/p99/mean change-visibility latency (seconds) over sampled writes.

    A (sample, node) pair that never became visible counts as +inf — if any
    exist, ``unseen`` reports them and the percentiles are taken over seen
    pairs only (callers should treat unseen > 0 as non-convergence).
    """
    vis = np.asarray(final.vis_round)  # [S, N]
    if vis.size == 0:
        return {"p50_s": float("nan"), "p99_s": float("nan"),
                "mean_s": float("nan"), "unseen": 0, "pairs": 0}
    alive = np.asarray(final.swim.alive)
    if alive_only:
        vis = vis[:, alive]
    lat_rounds = vis - schedule.sample_round[:, None]
    seen = vis >= 0
    lat = lat_rounds[seen].astype(np.float64) * (cfg.round_ms / 1000.0)
    return {
        "p50_s": float(np.percentile(lat, 50)) if lat.size else float("nan"),
        "p99_s": float(np.percentile(lat, 99)) if lat.size else float("nan"),
        "mean_s": float(lat.mean()) if lat.size else float("nan"),
        "unseen": int((~seen).sum()),
        "pairs": int(seen.size),
    }
