"""Kernel telemetry plane shared by all simulation engines.

The host-agent plane already exports ~41 Prometheus series and W3C trace
propagation (utils/metrics.py, utils/tracing.py, docs/OBSERVABILITY.md);
this module gives the JAX kernel plane — the whole-cluster simulator —
the same observability surface:

- **RoundCurves schema**: one canonical per-round stats contract
  (``ROUND_CURVE_KEYS``) that the ``lax.scan`` bodies of
  ``sim.engine``, ``sim.sparse_engine``, ``sim.chunk_engine``, and
  ``sim.mixed_engine`` all populate (``round_curves`` zero-fills what an
  engine doesn't have, so the key set is identical everywhere and
  downstream consumers never branch per engine). The schema carries two
  planes: the PR 1 performance keys and the convergence *health* keys
  (``HEALTH_CURVE_KEYS``: staleness lag, SWIM health counters, backlog
  mass, and a fixed-bucket delivery-latency histogram) analyzed
  host-side by ``sim.health.ConvergenceReport``.
- **FlightRecorder**: streams per-round curves to JSONL at every chunk
  boundary of a chunked run. Long 100k-node runs report progress instead
  of going dark for minutes, and a crashed run leaves a replayable
  record (``replay_flight`` tolerates a truncated final line).
- **Metrics bridge**: ``publish_curves`` folds finished-run curves into
  a ``MetricsRegistry`` as ``corro_kernel_*`` counters/gauges rendered
  on the same Prometheus endpoint as the agent series.
- **Tracer spans**: each chunk execution opens a ``kernel_chunk`` span,
  so kernel runs appear in the same trace stream as agent sync sessions.
- **Plane attribution**: ``attribute_planes`` times a composite step
  with stages enabled cumulatively in execution order (moved here from
  bench.py); stage increments telescope exactly —
  ``overhead + sum(increments) == full`` — and ``PlaneAttribution.scale``
  projects the measured fractions onto a run's real per-round wall so
  ``sum(plane_ms) + residual_ms == step_ms`` holds by construction.

Everything here is host-side: nothing below traces into the jitted round
step except ``round_curves`` (a dict constructor) and ``jax.named_scope``
annotations added by the engines themselves.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from dataclasses import dataclass, field
from typing import IO, Callable

import jax
import jax.numpy as jnp
import numpy as np

# Delivery-latency histogram bucket upper edges, in ROUNDS (fixed at
# trace time so the on-device bucketize is shape-static; one extra
# overflow bucket catches everything past the last edge). With the
# default 500 ms round these cover 0.5 s .. 32 s — the reference's
# "how fast is a write visible cluster-wide" operating range.
VIS_LAT_EDGES = (1, 2, 4, 8, 16, 32, 64)
VIS_LAT_KEYS = tuple(f"vis_lat_b{i}" for i in range(len(VIS_LAT_EDGES) + 1))

# Chaos plane (sim/faults.py): ground-truth fault-injection observables
# emitted from inside the scan bodies so a flight record carries the
# adversary's actions next to the protocol's reactions (docs/CHAOS.md).
CHAOS_CURVE_KEYS = (
    "chaos_lost_msgs",  # messages dropped by injected/ambient loss
    "chaos_wiped",  # nodes crash-wiped this round
)

# Convergence health plane (PR 2): protocol-level observables computed
# on-device inside every engine's scan body. Published under the
# ``corro_kernel_health_*`` prefix (see ``series_name``); semantics per
# key in docs/OBSERVABILITY.md ("Convergence plane").
HEALTH_CURVE_KEYS = (
    "staleness_sum",  # Σ per-node (head - contig watermark) gap, level
    "staleness_max",  # max per-node watermark gap, level
    "swim_false_alarms",  # (live obs, ALIVE target) believed suspect/down
    "swim_undetected_deaths",  # (live obs, DEAD target) still believed up
    "swim_flaps",  # refutation-driven incarnation bumps this round
    "queue_backlog",  # occupied pending-broadcast queue slots, level
    "streams_applied",  # (node, stream) pairs fully reassembled, level
    "chunks_sent",  # chunk-plane chunks gossiped this round
    "seqs_granted",  # chunk-plane seqs granted by partial-need sync
) + CHAOS_CURVE_KEYS + VIS_LAT_KEYS

# Multi-chip scale-out plane (parallel/shard_driver.py): exact per-round
# cross-shard byte volume of the explicit broadcast queue exchange,
# split by mesh axis (ici = innermost/fast hop, dcn = coalesced outer
# hop(s)). Zero under the single-host and GSPMD drivers — a nonzero
# value certifies the shard_map delivery path ran. f32 (byte counts at
# 100k-node shapes exceed u32).
XSHARD_CURVE_KEYS = (
    "xshard_bytes_ici",  # queue-exchange bytes over the fast axis
    "xshard_bytes_dcn",  # queue-exchange bytes across dcn groups
)

# Propagation-topology plane (docs/OBSERVABILITY.md "Propagation
# plane"): epidemic *structure* observables, opt-in per config
# (``GossipConfig.prop_observe`` / ``ChunkConfig.prop_observe``) with
# the chaos axes' static zero-cost-skip contract — a disabled config
# emits constants and traces no extra work. Region count is bounded by
# ``PROP_REGIONS`` (the fixed committed-scenario geography); larger
# topologies must keep the plane off or shrink their region axis.
PROP_REGIONS = 4

# Per-round region-pair traffic matrix, row = receiver region, col =
# source region, flattened row-major into fixed scalar keys so the
# matrix rides the canonical RoundCurves schema (CT010-checkable).
# Entries beyond a scenario's actual region count stay zero.
LINK_CURVE_KEYS = tuple(
    f"link_{i}{j}" for i in range(PROP_REGIONS) for j in range(PROP_REGIONS)
)

# Rumor-age histogram bucket upper edges, in ROUNDS: age since commit at
# FIRST delivery (watermark crossing or window possession) per tracked
# (sample, node) pair. Same shape-static bucketize machinery as
# VIS_LAT_EDGES but finer — the epidemic analyzer (obs/epidemic.py)
# reconstructs the coverage curve S(t) from these buckets, and the
# logistic fit needs resolution around the half-coverage knee.
RUMOR_AGE_EDGES = (1, 2, 3, 4, 5, 6, 8, 10, 12, 16, 24, 32, 48, 64)
RUMOR_AGE_KEYS = tuple(
    f"rumor_age_b{i}" for i in range(len(RUMOR_AGE_EDGES) + 1)
)

# Effective-fanout counters: of the broadcast copies delivered this
# round, how many were NEW to their receiver (first receipt of a newly
# possessed version — the epidemic's productive pushes) vs redundant
# (stale / duplicate / far-ahead copies). dup / (useful + dup) is the
# wasted-push ratio the SI model predicts grows as coverage saturates.
PROP_CURVE_KEYS = (
    "prop_useful_msgs",
    "prop_dup_msgs",
) + LINK_CURVE_KEYS + RUMOR_AGE_KEYS + (
    # Adaptive-dissemination mechanism counters (exactly zero while the
    # mechanisms are disabled): pending-queue entries retired by the
    # duplicate-receipt kill (cfg.rumor_kill_k) and nodes whose
    # far-fanout slots flipped push->pull this round
    # (cfg.pull_switch_age). docs/PERFORMANCE.md "Adaptive
    # dissemination" has the mechanism definitions.
    "prop_rumor_kills",
    "prop_pull_rounds",
)

# Canonical per-round curve keys. Every engine's scan body emits exactly
# this set (superset of the former ad-hoc dicts); semantics per key are
# documented in docs/OBSERVABILITY.md ("Kernel plane" + "Convergence
# plane").
ROUND_CURVE_KEYS = (
    "msgs",
    "applied_broadcast",
    "applied_sync",
    "cell_merges",
    "need",
    "mismatches",
    "sessions",
    "window_degraded",
    "sync_regrant",
    "cold_healed",
    "vis_count",
) + HEALTH_CURVE_KEYS + XSHARD_CURVE_KEYS + PROP_CURVE_KEYS

# Level-style curves whose end-of-run value is a convergence verdict on
# its own: published additionally as ``<series>_last`` gauges.
LEVEL_CURVE_KEYS = (
    "need",
    "mismatches",
    "staleness_sum",
    "staleness_max",
    "swim_false_alarms",
    "swim_undetected_deaths",
    "queue_backlog",
    "streams_applied",
)


def series_name(key: str) -> str:
    """Prometheus series stem for a canonical curve key.

    PR 1 performance keys render as ``corro_kernel_<key>``; the
    convergence health plane renders as ``corro_kernel_health_<key>`` so
    dashboards can scrape the protocol-health surface as one family.
    """
    prefix = (
        "corro_kernel_health_" if key in HEALTH_CURVE_KEYS
        else "corro_kernel_"
    )
    return prefix + key


def delivery_latency_hist(lat_rounds, newly, edges=None, keys=None) -> dict:
    """Fixed-bucket delivery-latency histogram for one round, on-device.

    ``lat_rounds`` (int[...]) is commit-to-visible latency in rounds for
    every tracked pair; ``newly`` (bool[...], same shape) masks the pairs
    that became visible THIS round. Bucket b counts newly-visible pairs
    with ``edges[b-1] < lat <= edges[b]`` (b0 = ``lat <= edges[0]``; the
    final bucket is the overflow past the last edge). Shape-static
    bucketize + one-hot sum — a handful of elementwise compares and
    reductions, TPU-friendly inside a scan body. Defaults to the
    ``VIS_LAT_EDGES``/``VIS_LAT_KEYS`` pair; the propagation plane
    reuses the machinery with the finer ``RUMOR_AGE_EDGES`` buckets.
    Returns ``{keys[0]: u32, ...}`` ready for ``round_curves``.
    """
    edges = VIS_LAT_EDGES if edges is None else edges
    keys = VIS_LAT_KEYS if keys is None else keys
    lat = lat_rounds.astype(jnp.int32)
    idx = jnp.zeros(lat.shape, jnp.int32)
    for e in edges:
        idx = idx + (lat > e).astype(jnp.int32)
    return {
        k: jnp.sum(newly & (idx == b), dtype=jnp.uint32)
        for b, k in enumerate(keys)
    }


def link_curves(link) -> dict:
    """Flatten a [R, R] region-pair traffic matrix (R <= PROP_REGIONS)
    into the fixed ``LINK_CURVE_KEYS`` scalars; entries beyond the
    scenario's region count zero-fill so the flattened key set is
    shape-independent."""
    r = link.shape[0]
    if r > PROP_REGIONS:
        raise ValueError(
            f"propagation plane supports at most {PROP_REGIONS} regions, "
            f"got {r}; disable prop_observe or shrink the region axis"
        )
    return {
        f"link_{i}{j}": (
            link[i, j] if i < r and j < r else jnp.uint32(0)
        )
        for i in range(PROP_REGIONS)
        for j in range(PROP_REGIONS)
    }


def prop_curves(
    enabled: bool, link, useful, dup, lat_rounds, newly,
    kills=None, pulls=None,
) -> dict:
    """Per-round propagation-plane stats for a scan body, or {} when the
    plane is disabled (the static zero-cost skip: nothing traces).

    ``link`` is the [R, R] delivered-copies matrix (receiver region row,
    source region column), ``useful``/``dup`` the effective-fanout
    split, and ``lat_rounds``/``newly`` feed the rumor-age histogram —
    ages since commit of the pairs first delivered THIS round, on the
    ``RUMOR_AGE_EDGES`` buckets. ``kills``/``pulls`` are the adaptive-
    dissemination mechanism counters (None — engines without the
    mechanisms, e.g. the chunk plane — emits zeros, matching the
    mechanisms-off contract). The analysis plane (CT010) resolves a
    ``**prop_curves(...)`` expansion to ``PROP_CURVE_KEYS`` statically,
    so schema parity stays checkable.
    """
    if not enabled:
        return {}
    out = {
        "prop_useful_msgs": useful.astype(jnp.uint32),
        "prop_dup_msgs": dup.astype(jnp.uint32),
        "prop_rumor_kills": (
            jnp.uint32(0) if kills is None else kills.astype(jnp.uint32)
        ),
        "prop_pull_rounds": (
            jnp.uint32(0) if pulls is None else pulls.astype(jnp.uint32)
        ),
    }
    out.update(link_curves(link))
    out.update(
        delivery_latency_hist(
            lat_rounds, newly, edges=RUMOR_AGE_EDGES, keys=RUMOR_AGE_KEYS
        )
    )
    return out


def round_curves(**stats) -> dict:
    """Build a canonical per-round stats dict for a scan body.

    Unknown keys raise (schema drift fails loudly at trace time); missing
    keys zero-fill, so engines only state what their plane measures.
    """
    unknown = set(stats) - set(ROUND_CURVE_KEYS)
    if unknown:
        raise ValueError(
            f"unknown round-curve keys {sorted(unknown)}; canonical set is "
            f"{ROUND_CURVE_KEYS}"
        )
    return {
        k: stats[k] if k in stats else jnp.uint32(0)
        for k in ROUND_CURVE_KEYS
    }


def curve_array(curves: dict, key: str) -> np.ndarray:
    """Curve as float64, zero-filled to the record's round count when
    the key is absent (old flight files predating a plane replay as
    all-zero for it) — the one fallback convention every host-side
    analyzer (sim/health.py, obs/epidemic.py) shares."""
    if key in curves:
        return np.asarray(curves[key], dtype=np.float64)
    n = len(np.asarray(curves.get("round", curves.get("msgs", []))))
    return np.zeros(n, dtype=np.float64)


FLIGHT_SCHEMA = "corro-flight/1"


def flight_segments(path: str) -> list[str]:
    """Every file of a (possibly rotated) flight record, oldest first:
    ``path.1``, ``path.2``, ..., then the live ``path``. Non-numeric
    suffixes are not segments."""
    import glob as _glob

    segs = []
    for p in _glob.glob(path + ".*"):
        sfx = p[len(path) + 1:]
        if sfx.isdigit():
            segs.append((int(sfx), p))
    out = [p for _n, p in sorted(segs)]
    if os.path.exists(path):
        out.append(path)
    return out


class FlightRecorder:
    """Streams per-round kernel curves to JSONL at chunk boundaries.

    One ``{"kind": "round", "round": r, <curve values>}`` object per
    round, plus a ``{"kind": "chunk", ...}`` marker per flushed chunk
    (device-execution wall included) and a ``{"kind": "flight", ...}``
    header per open — the header is self-describing (``schema``
    ``corro-flight/1`` + ``segment``), so a reader can refuse a future
    incompatible format instead of misparsing it. The file is flushed
    after every chunk, so a crashed run loses at most the in-flight
    chunk and the tail line may be truncated mid-write —
    ``replay_flight`` skips unparsable lines.

    Open with ``mode="a"`` (default) to let a resumed run append to the
    same record.

    **Rotation** (``max_bytes``): an hours-long soak must not grow one
    unbounded JSONL. Past the cap (checked at chunk boundaries — whole
    chunks are never split across files), the live file rotates to
    ``path.N`` (N monotonically increasing, oldest = ``.1``) and a fresh
    ``path`` opens with a new header carrying the next ``segment``
    index. ``replay_flight`` reads the whole segment chain
    transparently; rounds stay absolute across segments.
    """

    def __init__(
        self, path: str, engine: str = "dense", mode: str = "a",
        max_bytes: int | None = None,
    ):
        self.path = path
        self.engine = engine
        self.max_bytes = max_bytes
        existing = flight_segments(path)
        if mode == "w":
            # A truncating open starts a FRESH record: stale rotated
            # segments from a previous capped run at the same path must
            # not survive to be merged into this record's replay.
            for p in existing:
                if p != path:
                    os.remove(p)
            self._segment = 0
        else:
            # Resume-aware segment counter: appending to an already-
            # rotated record must not rename the live file over an old
            # segment.
            self._segment = max(
                (
                    int(p[len(path) + 1:]) for p in existing
                    if p != path
                ),
                default=0,
            )
        self._f: IO[str] | None = open(path, mode)
        self._write_header()
        self._f.flush()

    def _write_header(self) -> None:
        self._write(
            {"kind": "flight", "schema": FLIGHT_SCHEMA, "version": 1,
             "engine": self.engine, "segment": self._segment,
             "t_unix": time.time()}
        )

    def _write(self, obj: dict) -> None:
        # Flush every record: `obs tail` / external `tail -f` must see
        # progress as it happens, not at close. Records are flushed in
        # whole lines, so a live reader only ever races the final
        # in-flight line (which replay_flight and iter_flight skip).
        self._f.write(json.dumps(obj) + "\n")
        self._f.flush()

    def record_event(self, obj: dict) -> None:
        """Append one out-of-band event line (e.g. the compile ledger's
        ``kind: "compile"`` records). The reserved kinds stay owned by
        their writers so replay_flight's row semantics cannot be
        spoofed."""
        if self._f is None:
            raise ValueError("FlightRecorder is closed")
        if obj.get("kind") in ("round", "chunk", "flight"):
            raise ValueError(
                f"record_event cannot write reserved kind {obj.get('kind')!r}"
            )
        self._write(obj)

    def record_chunk(
        self, start_round: int, curves: dict, wall_s: float | None = None
    ) -> None:
        """Flush one chunk's per-round curves (rounds are absolute)."""
        if self._f is None:
            raise ValueError("FlightRecorder is closed")
        keys = [k for k in ROUND_CURVE_KEYS if k in curves]
        n = len(np.asarray(curves[keys[0]])) if keys else 0
        cols = {k: np.asarray(curves[k]) for k in keys}
        for i in range(n):
            obj = {"kind": "round", "round": int(start_round) + i}
            for k in keys:
                v = cols[k][i]
                obj[k] = float(v) if np.issubdtype(
                    cols[k].dtype, np.floating
                ) else int(v)
            self._write(obj)
        marker = {"kind": "chunk", "start": int(start_round), "rounds": n}
        if wall_s is not None:
            marker["wall_s"] = round(float(wall_s), 6)
        self._write(marker)
        self._f.flush()
        if (
            self.max_bytes is not None
            and self._f.tell() >= self.max_bytes
        ):
            self._rotate()

    def _rotate(self) -> None:
        """Roll the live file to ``path.N`` and open a fresh segment.
        Only called at chunk boundaries, so every segment holds whole
        chunks and replays standalone."""
        self._f.close()
        self._segment += 1
        os.replace(self.path, f"{self.path}.{self._segment}")
        self._f = open(self.path, "w")
        self._write_header()
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self) -> "FlightRecorder":
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def replay_flight(path: str) -> tuple[dict, list[dict]]:
    """Rebuild (curves, chunk markers) from a flight-recorder JSONL —
    including every rotated segment (``path.1``, ``path.2``, ...; see
    FlightRecorder rotation), oldest first.

    Crash-tolerant: unparsable lines (a write cut mid-line) are skipped.
    Rounds are sorted by absolute index; duplicate rounds (an overlapping
    re-run) keep the last record. Curve arrays carry only the keys the
    file actually recorded.
    """
    rows: dict[int, dict] = {}
    chunks: list[dict] = []
    for seg in flight_segments(path) or [path]:
        with open(seg) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue  # truncated tail from a crash — ignore
                kind = obj.get("kind")
                if kind == "round" and "round" in obj:
                    rows[int(obj["round"])] = obj
                elif kind == "chunk":
                    chunks.append(obj)
    order = sorted(rows)
    keys = [
        k for k in ROUND_CURVE_KEYS
        if any(k in rows[r] for r in order)
    ]
    curves = {
        k: np.asarray([rows[r].get(k, 0) for r in order])
        for k in keys
    }
    curves["round"] = np.asarray(order, np.int64)
    return curves, chunks


def publish_curves(registry, curves: dict, engine: str = "dense") -> None:
    """Fold finished-run curves into a MetricsRegistry.

    Per canonical key: a ``<series>_total{engine=...}`` counter holding
    the run's summed curve, where ``<series>`` is ``series_name(key)``
    (``corro_kernel_<key>`` for the performance plane,
    ``corro_kernel_health_<key>`` for the convergence health plane).
    Level-style curves (``LEVEL_CURVE_KEYS``) additionally set
    ``<series>_last{engine=...}`` gauges to their end-of-run value
    (their sums are still published so totals always equal summed
    curves). ``corro_kernel_rounds_total`` counts simulated rounds.

    The propagation plane's per-link and per-bucket curves stay in the
    flight record only (16 + 15 series per engine would bloat the
    scrape surface); the metrics bridge carries their AGGREGATES
    instead: ``corro_kernel_prop_link_same_region_total`` /
    ``corro_kernel_prop_link_cross_region_total`` (delivered copies by
    region relation) and ``corro_kernel_prop_rumor_events_total``
    (first deliveries the rumor-age histogram bucketed).
    """
    link_total = {"same": 0.0, "cross": 0.0}
    link_seen = False
    rumor_total = 0.0
    rumor_seen = False
    n = 0
    for k in ROUND_CURVE_KEYS:
        if k not in curves:
            continue
        if k in LINK_CURVE_KEYS:
            link_seen = True
            i, j = k[len("link_"):]
            rel = "same" if i == j else "cross"
            link_total[rel] += float(
                np.asarray(curves[k], dtype=np.float64).sum()
            )
            continue
        if k in RUMOR_AGE_KEYS:
            rumor_seen = True
            rumor_total += float(
                np.asarray(curves[k], dtype=np.float64).sum()
            )
            continue
        arr = np.asarray(curves[k], dtype=np.float64)
        n = max(n, arr.size)
        registry.counter(
            f"{series_name(k)}_total",
            f"kernel plane: summed per-round {k}",
        ).inc(float(arr.sum()), engine=engine)
        if k in LEVEL_CURVE_KEYS and arr.size:
            registry.gauge(
                f"{series_name(k)}_last",
                f"kernel plane: end-of-run {k}",
            ).set(float(arr[-1]), engine=engine)
    if link_seen:
        for rel, help_ in (
            ("same", "within one region"), ("cross", "between regions"),
        ):
            registry.counter(
                f"corro_kernel_prop_link_{rel}_region_total",
                f"propagation plane: delivered copies {help_}",
            ).inc(link_total[rel], engine=engine)
    if rumor_seen:
        registry.counter(
            "corro_kernel_prop_rumor_events_total",
            "propagation plane: first deliveries bucketed by rumor age",
        ).inc(rumor_total, engine=engine)
    registry.counter(
        "corro_kernel_rounds_total", "kernel plane: simulated rounds"
    ).inc(float(n), engine=engine)


@dataclass
class KernelTelemetry:
    """Bundle of per-run telemetry sinks threaded through an engine.

    Any subset may be enabled: ``recorder`` streams JSONL per chunk,
    ``registry`` receives ``corro_kernel_*`` series at run end (and a
    ``corro_kernel_chunk_seconds`` histogram per chunk), ``tracer`` opens
    a ``kernel_chunk`` span around each device execution, ``progress``
    gets one status line per chunk (the anti-going-dark channel for long
    runs). ``chunk_walls`` accumulates (rounds, wall seconds) per chunk —
    ``device_step_ms`` is the instrumented per-round step time over the
    chunk execution windows only, which is why it is a lower bound on a
    caller's whole-run wall per round.

    ``ledger`` (obs.ledger.CompileLedger) opens a compile window around
    every chunk execution: compilation events are attributed to the
    chunk that dispatched them, written to the flight recorder
    (``kind: "compile"``) and counted into the registry as
    ``corro_kernel_compiles_total`` / ``corro_kernel_compile_ms``. An
    ARMED ledger turns any steady-state compile into a RetraceError —
    the run fails loudly instead of silently eating wall.

    ``watermarks`` (obs.costs.MemoryWatermarks) samples live per-device
    buffer bytes at every chunk boundary — the measured side of the
    predicted-vs-live memory reconciliation (obs.costs.reconcile_memory).

    ``series`` (obs.series.MetricSeriesRecorder, duck-typed so this
    module never imports obs) flushes one whole-registry snapshot per
    chunk boundary with ``t`` = the absolute post-chunk round index:
    the endurance plane's kernel lane. Level-curve ``_last`` gauges are
    refreshed from the chunk tail FIRST, so the series carries the
    convergence watermarks as they move, not only at run end. With a
    clock-less recorder and ``series_exclude`` dropping the wall-clock
    chunk histogram (the default), a seeded rerun reproduces the series
    file byte for byte.
    """

    engine: str = "dense"
    recorder: FlightRecorder | None = None
    registry: object | None = None
    tracer: object | None = None
    progress: IO[str] | None = None
    chunk_walls: list = field(default_factory=list)
    ledger: object | None = None
    watermarks: object | None = None
    series: object | None = None
    series_exclude: tuple = ("corro_kernel_chunk_seconds",)

    def run_chunk(self, start_round: int, fn: Callable):
        """Execute one chunk ``fn() -> (state, curves)`` under a span,
        time it to completion (blocks on the returned state), then flush
        the chunk to every enabled sink."""
        span_cm = (
            self.tracer.span(
                "kernel_chunk", engine=self.engine,
                start_round=int(start_round),
            )
            if self.tracer is not None
            else contextlib.nullcontext()
        )
        ledger_cm = (
            self.ledger.window(f"{self.engine}@r{int(start_round)}")
            if self.ledger is not None
            else contextlib.nullcontext()
        )
        t0 = time.perf_counter()
        with span_cm as span, ledger_cm as cwin:
            state, curves = fn()
            jax.block_until_ready(jax.tree.leaves(state))
            # Close the timed window before any host-side curve reads so
            # the wall stays execution-only.
            wall = time.perf_counter() - t0
            n = len(np.asarray(next(iter(curves.values())))) if curves else 0
            if span is not None:
                span.set_attr("rounds", n)
                span.set_attr("wall_s", round(wall, 6))
        if self.watermarks is not None:
            # Chunk boundary: the carried state (and the freshly stacked
            # curves) are live right now — the honest high-water moment.
            self.watermarks.sample()
        if (
            cwin is not None and not cwin.nested
            and (cwin.compiles or cwin.fns)
        ):
            # A nested placeholder window (this chunk ran inside a
            # caller's own ledger window, which owns the attribution)
            # reports nothing here — the outer scope's reader and
            # ledger.publish() cover it exactly once.
            if self.recorder is not None:
                self.recorder.record_event(cwin.to_record())
            if self.registry is not None:
                self.ledger.publish_window(
                    self.registry, cwin, engine=self.engine
                )
        self.on_chunk(start_round, curves, wall, n_rounds=n)
        return state, curves

    def on_chunk(
        self, start_round: int, curves: dict, wall_s: float,
        n_rounds: int | None = None,
    ) -> None:
        n = (
            n_rounds
            if n_rounds is not None
            else len(np.asarray(next(iter(curves.values())))) if curves else 0
        )
        self.chunk_walls.append((n, wall_s))
        if self.registry is not None:
            self.registry.histogram(
                "corro_kernel_chunk_seconds",
                "kernel plane: wall seconds per chunk execution",
            ).observe(wall_s, engine=self.engine)
        if self.recorder is not None:
            self.recorder.record_chunk(start_round, curves, wall_s)
        if self.series is not None and self.registry is not None:
            # Refresh the level-gauge watermarks from the chunk tail
            # (same names publish_curves sets at run end), then flush
            # one snapshot at t = absolute round index — deterministic
            # for a seeded run once the wall-clock histogram is
            # excluded.
            for k in LEVEL_CURVE_KEYS:
                if k in curves and n:
                    self.registry.gauge(
                        f"{series_name(k)}_last",
                        f"kernel plane: end-of-run {k}",
                    ).set(
                        float(np.asarray(curves[k])[-1]),
                        engine=self.engine,
                    )
            self.series.sample(
                self.registry,
                t=float(int(start_round) + n),
                exclude=self.series_exclude,
            )
        if self.progress is not None:
            tail = {
                k: int(np.asarray(curves[k])[-1])
                for k in ("need", "mismatches") if k in curves and n
            }
            msgs = (
                int(np.asarray(curves["msgs"]).sum())
                if "msgs" in curves else 0
            )
            self.progress.write(
                f"[flight:{self.engine}] rounds "
                f"{int(start_round)}..{int(start_round) + n - 1} "
                f"wall={wall_s:.2f}s msgs={msgs} {json.dumps(tail)}\n"
            )
            self.progress.flush()

    def on_run_end(self, curves: dict) -> None:
        if self.registry is not None:
            publish_curves(self.registry, curves, engine=self.engine)

    @property
    def device_step_ms(self) -> float:
        """Per-round wall over the instrumented chunk executions only
        (excludes host work between chunks: schedule slicing, curve
        merging, planner bookkeeping)."""
        rounds = sum(n for n, _ in self.chunk_walls)
        if rounds == 0:
            return float("nan")
        return sum(w for _, w in self.chunk_walls) / rounds * 1000.0


def owned_copy(tree):
    """Distinct-buffer deep copy of a state pytree: safe to donate.

    The one implementation of the copy-once-donate-always ownership rule
    every engine driver applies to its first carry (docs/PERFORMANCE.md
    "Donation invariants"): freshly-built init states can share one
    constant buffer between identical zero-filled leaves (XLA rejects
    donating it twice), and caller-supplied resume states must stay
    readable after the run — one copy makes the carry donatable, and
    every later chunk/epoch donates the previous execution's output
    without copying.
    """
    return jax.tree.map(jnp.copy, tree)


def flight_path_from_argv(
    argv, default: str = "flight.jsonl"
) -> str | None:
    """Shared ``--flight`` CLI parsing for the smoke scripts.

    Accepts ``--flight`` (recorder at ``default``) or ``--flight=PATH``.
    The path is never taken from a separate token, so a following
    positional (e.g. a rounds count) is never swallowed. Returns None
    when the flag is absent.
    """
    for a in argv:
        if a == "--flight":
            return default
        if a.startswith("--flight="):
            return a.split("=", 1)[1] or default
    return None


# ---------------------------------------------------------------------------
# Plane attribution (moved from bench.py so every engine can reuse it).


def time_scan_step(step, carry, iters: int = 10) -> float:
    """Time ``step`` by scanning it inside ONE jitted computation:
    per-call dispatch to a (possibly remote) device costs hundreds of ms
    and would otherwise dominate. Returns warm ms per iteration."""
    from functools import partial

    @partial(jax.jit, static_argnames=("n",))
    def scan(carry, n):
        def body(c, i):
            return step(c, i), ()

        out, _ = jax.lax.scan(body, carry, jnp.arange(n))
        return out

    out = scan(carry, iters)  # compile
    jax.block_until_ready(jax.tree.leaves(out))
    t0 = time.perf_counter()
    out = scan(carry, iters)
    jax.block_until_ready(jax.tree.leaves(out))
    return (time.perf_counter() - t0) / iters * 1000.0


@dataclass(frozen=True)
class PlaneAttribution:
    """Cumulative-prefix stage timings for a composite step.

    ``cum_ms[k]`` is the measured per-iteration wall with the first ``k``
    stages enabled (``cum_ms[0]`` = empty-scan overhead). Increments
    telescope to the full composite EXACTLY:
    ``overhead_ms + sum(increments) == full_ms`` is an identity of the
    construction, asserted in ``check`` so regressions in the harness
    itself fail loudly.
    """

    stages: tuple
    cum_ms: tuple

    @property
    def full_ms(self) -> float:
        return self.cum_ms[-1]

    @property
    def overhead_ms(self) -> float:
        return self.cum_ms[0]

    @property
    def increments(self) -> dict:
        return {
            s: self.cum_ms[k + 1] - self.cum_ms[k]
            for k, s in enumerate(self.stages)
        }

    def check(self, tol: float = 1e-9) -> None:
        total = self.overhead_ms + sum(self.increments.values())
        assert abs(total - self.full_ms) <= tol * max(abs(self.full_ms), 1.0), (
            f"telescoping broken: overhead {self.overhead_ms} + increments "
            f"{self.increments} != full {self.full_ms}"
        )

    def scale(self, step_ms: float) -> tuple[dict, float]:
        """Project measured stage fractions onto a run's real per-round
        wall. Returns ``(plane_ms, residual_ms)`` with the invariant
        ``sum(plane_ms) + residual_ms == step_ms`` exact by construction;
        the residual carries the empty-scan overhead, timer-noise
        clamping, and any host dispatch the composite can't see."""
        self.check()
        if self.full_ms <= 0:
            return {s: 0.0 for s in self.stages}, step_ms
        plane = {
            s: max(inc, 0.0) / self.full_ms * step_ms
            for s, inc in self.increments.items()
        }
        residual = step_ms - sum(plane.values())
        assert abs(sum(plane.values()) + residual - step_ms) <= 1e-9 * max(
            abs(step_ms), 1.0
        )
        return plane, residual


def check_bench_invariants(
    report: dict, tol: float = 1e-6, extra_provenance: tuple = ()
) -> dict:
    """Assert the documented step-time invariants on an emitted bench
    report (bench.py module docstring), exactly as they appear in the
    JSON, and return the report unchanged so the emit site can wrap it.

    **Provenance**: every report must be self-describing — ``platform``
    (the jax device platform the numbers were measured on), ``nodes``,
    ``device_count``, and ``config_fingerprint`` (a stable hash of the
    measured configuration, ``benchlib.config_fingerprint``) are
    REQUIRED. The BENCH_r05 incident was a CPU-fallback run published
    under a TPU metric name; with these fields a fallback artifact is
    unmistakable and the budget gate can refuse cross-platform
    comparisons outright.

    Checked for the base fields and every suffixed variant present
    (``step_ms_100k``, ...):

    - ``step_inner_ms <= step_ms``: the device chunk-execution windows
      are a subset of the run wall, so the instrumented per-round time
      can never exceed the end-to-end one. (BENCH_r05 violated this —
      its reporting path published the raw composite microbench, an
      end-of-run-state sample, as step_inner_ms.)
    - ``sum(plane_ms.values()) + residual_ms == step_ms``: plane
      attribution is a partition of the measured step time; nothing may
      hide in unattributed time.
    - **Roofline** (the device-cost plane, obs/costs.py): a report that
      attributes step time to planes must also attribute device cost —
      a top-level ``plane_ms`` requires a ``roofline`` block with one
      entry per plane carrying ``flops``/``bytes``/``flops_per_s``/
      ``bytes_per_s``/``intensity``, and the achieved rates must equal
      ``flops (bytes) / plane_ms`` recomputed from the emitted numbers.
    - **Compile split** (the compile ledger): ``compile_ms`` requires
      ``first_step_ms``, both non-negative, and when
      ``first_run_incl_compile_s`` is present the split must
      reconstruct it: ``compile_ms + first_step_ms ==
      first_run_incl_compile_s * 1000`` on the emitted (rounded)
      numbers — the opaque first-run blob is exactly compile + run,
      nothing hides between them. ``compile_ms <= first_run`` follows.
    - **Steady state is compile-free**: a ``steady_compiles`` field
      must be 0 — the ledger counted a recompile inside an armed timed
      window, and a bench that recompiled mid-measurement must not
      publish at all.

    Raises ValueError naming the offending field on violation (a real
    exception, not ``assert`` — the guarantee must survive ``python -O``);
    the bench emits nothing rather than publishing a report that
    contradicts its own documentation.

    ``extra_provenance`` names additional fields a report class requires
    beyond the base four — the serving plane (loadgen) passes
    ``("scenario",)`` so a load report can never be published without
    saying which standing scenario produced it.
    """
    for field in (
        "platform", "nodes", "device_count", "config_fingerprint",
        *extra_provenance,
    ):
        v = report.get(field)
        if v is None or v == "":
            raise ValueError(
                f"bench report is missing provenance field {field!r}: "
                f"every emitted bench JSON must be self-describing "
                f"(platform, nodes, device_count, config_fingerprint) "
                f"so a CPU-fallback run can never pass as an "
                f"accelerator artifact"
            )
    suffixes = sorted(
        {
            k[len("step_ms"):]
            for k in report
            if k.startswith("step_ms")
        }
    )
    for sfx in suffixes:
        step = report[f"step_ms{sfx}"]
        inner = report.get(f"step_inner_ms{sfx}")
        if inner is not None and not inner <= step + tol:
            raise ValueError(
                f"step_inner_ms{sfx}={inner} > step_ms{sfx}={step}: "
                f"chunk-execution windows exceed the run wall"
            )
        plane = report.get(f"plane_ms{sfx}")
        if plane is not None:
            residual = report.get(f"residual_ms{sfx}", 0.0)
            total = sum(plane.values()) + residual
            if not abs(total - step) <= tol * max(abs(step), 1.0):
                raise ValueError(
                    f"plane_ms{sfx} {plane} + residual_ms{sfx} {residual} "
                    f"= {total} != step_ms{sfx} {step}: attribution must "
                    f"partition the measured step time"
                )

    # Roofline: a report attributing step time to planes must attribute
    # device cost the same way (suffixed variants — the 100k tail — are
    # timing-only extras and are exempt).
    plane = report.get("plane_ms")
    if plane is not None:
        roof = report.get("roofline")
        if not isinstance(roof, dict):
            raise ValueError(
                "report carries plane_ms but no roofline block: every "
                "plane attribution must also carry flops/bytes per plane "
                "(obs/costs.roofline_stage_costs + "
                "benchlib.roofline_report)"
            )
        missing = set(plane) - set(roof)
        if missing:
            raise ValueError(
                f"roofline is missing plane(s) {sorted(missing)}: the "
                f"flop/byte attribution must cover every timed plane"
            )
        for name, entry in roof.items():
            for f in ("flops", "bytes", "flops_per_s", "bytes_per_s",
                      "intensity"):
                if f not in entry:
                    raise ValueError(
                        f"roofline.{name} is missing {f!r}"
                    )
            ms = plane.get(name)
            if ms and entry["flops_per_s"] is not None:
                want = entry["flops"] / (ms / 1000.0)
                if abs(entry["flops_per_s"] - want) > 5e-3 * max(want, 1.0):
                    raise ValueError(
                        f"roofline.{name}.flops_per_s "
                        f"{entry['flops_per_s']} != flops/plane_ms "
                        f"{want:.1f}: achieved rates must be derived "
                        f"from the emitted numbers"
                    )
            if ms and entry["bytes_per_s"] is not None:
                want = entry["bytes"] / (ms / 1000.0)
                if abs(entry["bytes_per_s"] - want) > 5e-3 * max(want, 1.0):
                    raise ValueError(
                        f"roofline.{name}.bytes_per_s "
                        f"{entry['bytes_per_s']} != bytes/plane_ms "
                        f"{want:.1f}"
                    )

    # Compile split: the ledger's decomposition of the first-run blob.
    compile_ms = report.get("compile_ms")
    if compile_ms is not None:
        first_step = report.get("first_step_ms")
        if first_step is None:
            raise ValueError(
                "compile_ms without first_step_ms: the ledger split "
                "publishes both halves of the first-run blob or neither"
            )
        if compile_ms < 0 or first_step < 0:
            raise ValueError(
                f"negative compile split: compile_ms={compile_ms} "
                f"first_step_ms={first_step}"
            )
        first_run_s = report.get("first_run_incl_compile_s")
        if first_run_s is not None:
            total = compile_ms + first_step
            want = first_run_s * 1000.0
            # The emit site derives first_step_ms from the ROUNDED
            # values (benchlib.compile_split_report), so the published
            # split reconstructs the blob to rounding, not to luck.
            if abs(total - want) > 0.5 + tol * max(want, 1.0):
                raise ValueError(
                    f"compile_ms {compile_ms} + first_step_ms "
                    f"{first_step} = {total} != "
                    f"first_run_incl_compile_s*1000 = {want}: the split "
                    f"must reconstruct the first-run blob exactly"
                )

    steady = report.get("steady_compiles")
    if steady is not None and steady != 0:
        raise ValueError(
            f"steady_compiles={steady}: the compile ledger observed "
            f"recompilation inside the armed timed window — the "
            f"measurement is contaminated and must not publish "
            f"(docs/PERFORMANCE.md 'Compile ledger')"
        )
    return report


def attribute_planes(
    make_step, stages: tuple, carry, iters: int = 10
) -> PlaneAttribution:
    """Cumulative-prefix attribution: time ``make_step(enabled)`` with
    stages enabled one at a time in execution order; a stage's cost is
    the increment over the previous prefix. ``make_step(())`` must
    return a valid (possibly identity) step — its time is the scan
    overhead, kept visible as ``overhead_ms``."""
    cum = tuple(
        time_scan_step(make_step(tuple(stages[:k])), carry, iters)
        for k in range(len(stages) + 1)
    )
    attr = PlaneAttribution(stages=tuple(stages), cum_ms=cum)
    attr.check()
    return attr
