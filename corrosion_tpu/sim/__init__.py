"""Cluster-simulation engine: composes the membership (SWIM), data
(broadcast + anti-entropy), and CRDT-merge kernels into a single jitted
round step, scanned over a scripted workload, sharded over a device mesh.

This is the "flagship model" of the framework: a whole-Corrosion-cluster
forward step (SURVEY.md north star). One simulated round ≈ one broadcast
flush tick (500 ms in the reference, broadcast/mod.rs:373).
"""

from corrosion_tpu.sim.engine import (  # noqa: F401
    ClusterConfig,
    ClusterState,
    Schedule,
    cluster_round,
    init_cluster,
    simulate,
    visibility_latencies,
)
from corrosion_tpu.sim.health import (  # noqa: F401
    ConvergenceReport,
    diff_reports,
    publish_report,
    report_from_curves,
    report_from_flight,
)
from corrosion_tpu.sim.telemetry import (  # noqa: F401
    HEALTH_CURVE_KEYS,
    ROUND_CURVE_KEYS,
    VIS_LAT_EDGES,
    FlightRecorder,
    KernelTelemetry,
    publish_curves,
    replay_flight,
)
from corrosion_tpu.sim.trace import (  # noqa: F401
    Trace,
    replay,
    schedule_from_trace,
)
