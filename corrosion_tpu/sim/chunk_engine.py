"""Engine driver for the seq-chunk plane (BASELINE-3 chunked variant).

Runs ops/chunks.py — multi-chunk transactions gossiped as seq ranges with
partial-need sync (change.rs:8-116, sync.rs:248-266, agent.rs:2063-2151) —
as a scanned whole-cluster simulation with first-application tracking, the
same shape the main engine gives the version-granular plane. A stream is
"applied" at a node when its coverage is gap-free to last_seq (the
process_fully_buffered_changes trigger, agent.rs:1667-1806).

The scan body emits the canonical RoundCurves schema (sim/telemetry.py):
``msgs`` = chunks sent, ``applied_broadcast`` = chunks accepted by bounded
intake, ``applied_sync`` = seqs granted by partial-need sync, ``need`` =
remaining seq deficit to full coverage, ``vis_count`` = (node, stream)
pairs newly reassembled this round; membership/CRDT keys zero-fill (this
plane has no SWIM or cell state). Convergence-health keys: staleness is
in SEQS (``staleness_sum`` mirrors ``need``, ``staleness_max`` is the
worst node's deficit), ``streams_applied`` is the reassembly level,
``chunks_sent``/``seqs_granted`` carry the plane's own traffic names
(mixed runs keep them separable from version-plane keys), and the
delivery-latency histogram buckets the round each pair completed
(streams commit at round 0).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.ops import chunks as chunk_ops
from corrosion_tpu.ops.chunks import ChunkConfig
from corrosion_tpu.sim import telemetry as telemetry_mod
from corrosion_tpu.sim.telemetry import KernelTelemetry


def _scan_impl(state, vis, last_seq, alive, base_key, xs, cfg):
    """xs = (round_idx [E], alive_t [E, N] | None, loss [E] | None,
    wipe [E, N] | None); ``alive`` is the churn-free constant used when
    ``alive_t`` is absent (the chaos axes are trace-time optional, like
    every engine)."""

    def body(carry, x):
        st, vis = carry
        r, alive_t, lo, wp = x
        a = alive if alive_t is None else alive_t
        key = jax.random.fold_in(base_key, r)
        if wp is not None:
            # Crash-with-state-wipe: partial buffers are gone before the
            # round's gossip (ops/chunks.wipe_coverage).
            st = chunk_ops.wipe_coverage(st, wp, cfg)
        st, stats = chunk_ops.chunk_round(
            st, last_seq, a, r, key, cfg, loss=lo
        )
        with jax.named_scope("corro_track"):
            applied = chunk_ops.applied_mask(st, last_seq, cfg)
            newly = (vis < 0) & applied
            vis = jnp.where(newly, r, vis)
        # Propagation plane, degenerate single-region form (the chunk
        # plane has no geography): all gossiped chunks land in link_00,
        # intake-accepted chunks are the useful pushes, and rumor age =
        # the round a (node, stream) pair first reassembled (streams
        # commit at round 0). Static skip when cfg.prop_observe is off.
        # The adaptive-dissemination counters (prop_rumor_kills /
        # prop_pull_rounds) zero-fill via prop_curves defaults: the
        # chunk plane has no rebroadcast queue to kill from or pull to.
        prop_stats = telemetry_mod.prop_curves(
            cfg.prop_observe,
            stats["chunks_sent"].reshape(1, 1),
            stats["chunks_applied"],
            stats["chunks_sent"] - stats["chunks_applied"],
            jnp.broadcast_to(r, newly.shape),
            newly,
        )
        curves = telemetry_mod.round_curves(
            msgs=stats["chunks_sent"],
            applied_broadcast=stats["chunks_applied"],
            applied_sync=stats["seqs_granted"],
            sessions=stats["sessions"],
            need=stats["need_seqs"],
            vis_count=jnp.sum(newly, dtype=jnp.uint32),
            # Convergence health plane. Staleness is in SEQS here (the
            # plane's unit of need); streams commit at round 0, so a
            # pair's delivery latency is simply the round it completed.
            staleness_sum=stats["need_seqs"],
            staleness_max=stats["need_node_max"],
            streams_applied=stats["applied_nodes"],
            chunks_sent=stats["chunks_sent"],
            seqs_granted=stats["seqs_granted"],
            chaos_lost_msgs=stats["lost_msgs"],
            chaos_wiped=(
                jnp.uint32(0) if wp is None
                else jnp.sum(wp, dtype=jnp.uint32)
            ),
            **telemetry_mod.delivery_latency_hist(
                jnp.broadcast_to(r, newly.shape), newly
            ),
            **prop_stats,
        )
        return (st, vis), curves

    return jax.lax.scan(body, (state, vis), xs)


# The donated twin aliases the carried (state, vis) into the outputs so
# chunked runs round-trip coverage/visibility buffers in place;
# ``last_seq``/``alive`` are NOT donated (the driver re-feeds them every
# chunk). It is the driver's only scan entry (a second non-donating
# compile would double the first chunk's dominant cost); the first
# chunk's freshly-built carry is made donatable by one deep copy —
# zero-filled leaves can share one constant buffer, which XLA rejects as
# a double donation. The plain entry remains for ad-hoc callers.
_scan = partial(jax.jit, static_argnames=("cfg",))(_scan_impl)
_scan_donated = partial(
    jax.jit, static_argnames=("cfg",), donate_argnums=(0, 1)
)(_scan_impl)


def simulate_chunks(
    cfg: ChunkConfig,
    origin,
    last_seq,
    rounds: int,
    seed: int = 0,
    round_ms: float = 500.0,
    max_chunk: int | None = None,
    telemetry: KernelTelemetry | None = None,
    faults=None,
    state=None,
    vis=None,
    start_round: int = 0,
):
    """Run ``rounds`` chunk-plane rounds; returns (state, metrics dict).

    Metrics: applied coverage fraction, p50/p99 first-application latency in
    simulated seconds over all (node, stream) pairs (unapplied pairs counted
    in ``unapplied``), plus run totals derived from the canonical curves
    (``curves`` itself is returned under that key).

    ``max_chunk`` splits the run into several device executions (the state
    and visibility tensors carry across; per-round RNG keys fold the
    absolute round index, so results are identical either way), and
    ``telemetry`` instruments each execution as a chunk — timed, spanned,
    and flushed to the flight recorder.

    ``faults`` (sim.faults.FaultPlan or CompiledFaults) injects chunk
    loss (the plan's worst-region scalar — this plane has no region
    structure), kill/revive churn (dead nodes neither gossip nor sync),
    and crash-with-state-wipe (coverage reset; wiping a stream's last
    full holder makes it unrecoverable, so plans protect origins).
    Partition components are rejected loudly — there is no region
    topology to cut.

    ``state``/``vis`` supply pre-built carries — the multi-chip path
    places ``init_chunks`` output and the visibility latch on a mesh
    (``parallel.shard_chunk_state`` / node-major) and passes them in;
    everything else about the run is unchanged (GSPMD partitions the
    row-local chunk round, so curves stay bit-identical to the
    unsharded run — pinned in tests/test_shard_driver.py).

    ``start_round`` is the resume seam (the elastic plane's
    checkpoint-reshard driver): per-round RNG keys and the visibility
    latch fold ``start_round + r``, so running ``[0, k)`` then resuming
    ``[k, R)`` with the carried ``state``/``vis`` (returned under
    ``metrics["vis"]``) is bit-identical to the uninterrupted run. A
    resumed call takes the TAIL slice of any fault arrays (the plan is
    authored in absolute rounds; slice before compiling or pass
    pre-sliced CompiledFaults).
    """
    origin = jnp.asarray(origin, jnp.int32)
    last_seq = jnp.asarray(last_seq, jnp.int32)
    if state is None:
        state = chunk_ops.init_chunks(cfg, origin, last_seq)
    alive = jnp.ones((cfg.n_nodes,), bool)
    if vis is None:
        vis = jnp.full((cfg.n_nodes, cfg.n_streams), -1, jnp.int32)
    base_key = jax.random.PRNGKey(seed)

    alive_np = loss_np = wipe_np = None
    if faults is not None:
        from corrosion_tpu.sim import faults as faults_mod

        # A FaultPlan compiles at whatever region count its components
        # reference (region-targeted loss degrades to the worst-region
        # scalar below); CompiledFaults pass through as-is.
        c = (
            faults.compile(cfg.n_nodes, max(1, faults.max_region() + 1))
            if isinstance(faults, faults_mod.FaultPlan) else faults
        )
        if c.rounds != rounds:
            raise ValueError(
                f"fault plan rounds {c.rounds} != run rounds {rounds}"
            )
        if c.partition is not None:
            raise ValueError(
                "the chunk plane has no region topology; partition/flap "
                "components cannot apply here (use loss or churn)"
            )
        loss_np = c.loss_scalar
        if c.kill is not None or c.revive is not None:
            alive_np = c.alive_curve(cfg.n_nodes)
        wipe_np = c.wipe

    step = max_chunk if max_chunk is not None else max(rounds, 1)
    # rounds == 0 is a valid degenerate run: empty canonical curves.
    curve_parts: list[dict] = (
        [] if rounds > 0
        else [{k: np.zeros((0,)) for k in telemetry_mod.ROUND_CURVE_KEYS}]
    )
    owned = False  # first chunk's carry needs the ownership copy
    for r0 in range(0, rounds, step):
        nr = min(step, rounds - r0)
        sl = slice(r0, r0 + nr)
        xs = (
            jnp.arange(
                start_round + r0, start_round + r0 + nr, dtype=jnp.int32
            ),
            None if alive_np is None else jnp.asarray(alive_np[sl]),
            None if loss_np is None else jnp.asarray(
                loss_np[sl], jnp.float32
            ),
            None if wipe_np is None else jnp.asarray(wipe_np[sl]),
        )
        if not owned:
            state = telemetry_mod.owned_copy(state)
            vis = telemetry_mod.owned_copy(vis)
        if telemetry is None:
            (state, vis), curves = _scan_donated(
                state, vis, last_seq, alive, base_key, xs, cfg
            )
        else:
            def _run(state=state, vis=vis, xs=xs):
                (st, vi), curves = _scan_donated(
                    state, vis, last_seq, alive, base_key, xs, cfg
                )
                return (st, vi), curves

            (state, vis), curves = telemetry.run_chunk(
                start_round + r0, _run
            )
        owned = True
        curve_parts.append({k: np.asarray(v) for k, v in curves.items()})
    merged = {
        k: np.concatenate([p[k] for p in curve_parts])
        for k in curve_parts[0]
    }
    if telemetry is not None:
        telemetry.on_run_end(merged)

    vis_np = np.asarray(vis)
    applied = vis_np >= 0
    lat = vis_np[applied].astype(np.float64) * (round_ms / 1000.0)
    metrics = {
        "applied_frac": float(applied.mean()),
        "unapplied": int((~applied).sum()),
        "p50_s": float(np.percentile(lat, 50)) if lat.size else float("nan"),
        "p99_s": float(np.percentile(lat, 99)) if lat.size else float("nan"),
        "seqs_granted": int(merged["applied_sync"].sum()),
        "chunks_sent": int(merged["msgs"].sum()),
        "curves": merged,
        # The visibility latch is part of the resume carry (elastic
        # checkpoint/reshard): pass it back in as ``vis`` with
        # ``start_round`` advanced to continue bit-identically.
        "vis": vis,
    }
    return state, metrics
