"""Engine driver for the seq-chunk plane (BASELINE-3 chunked variant).

Runs ops/chunks.py — multi-chunk transactions gossiped as seq ranges with
partial-need sync (change.rs:8-116, sync.rs:248-266, agent.rs:2063-2151) —
as a scanned whole-cluster simulation with first-application tracking, the
same shape the main engine gives the version-granular plane. A stream is
"applied" at a node when its coverage is gap-free to last_seq (the
process_fully_buffered_changes trigger, agent.rs:1667-1806).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.ops import chunks as chunk_ops
from corrosion_tpu.ops.chunks import ChunkConfig


@partial(jax.jit, static_argnames=("cfg", "rounds"))
def _scan(state, last_seq, alive, base_key, cfg, rounds):
    def body(carry, r):
        st, vis = carry
        key = jax.random.fold_in(base_key, r)
        st, stats = chunk_ops.chunk_round(st, last_seq, alive, r, key, cfg)
        applied = chunk_ops.applied_mask(st, last_seq, cfg)
        vis = jnp.where((vis < 0) & applied, r, vis)
        return (st, vis), stats

    vis0 = jnp.full((cfg.n_nodes, cfg.n_streams), -1, jnp.int32)
    return jax.lax.scan(
        body, (state, vis0), jnp.arange(rounds, dtype=jnp.int32)
    )


def simulate_chunks(
    cfg: ChunkConfig,
    origin,
    last_seq,
    rounds: int,
    seed: int = 0,
    round_ms: float = 500.0,
):
    """Run ``rounds`` chunk-plane rounds; returns (state, metrics dict).

    Metrics: applied coverage fraction, p50/p99 first-application latency in
    simulated seconds over all (node, stream) pairs (unapplied pairs counted
    in ``unapplied``)."""
    origin = jnp.asarray(origin, jnp.int32)
    last_seq = jnp.asarray(last_seq, jnp.int32)
    state = chunk_ops.init_chunks(cfg, origin, last_seq)
    alive = jnp.ones((cfg.n_nodes,), bool)
    (state, vis), curves = _scan(
        state, last_seq, alive, jax.random.PRNGKey(seed), cfg, rounds
    )
    vis_np = np.asarray(vis)
    applied = vis_np >= 0
    lat = vis_np[applied].astype(np.float64) * (round_ms / 1000.0)
    metrics = {
        "applied_frac": float(applied.mean()),
        "unapplied": int((~applied).sum()),
        "p50_s": float(np.percentile(lat, 50)) if lat.size else float("nan"),
        "p99_s": float(np.percentile(lat, 99)) if lat.size else float("nan"),
        "seqs_granted": int(np.asarray(curves["seqs_granted"]).sum()),
        "chunks_sent": int(np.asarray(curves["chunks_sent"]).sum()),
    }
    return state, metrics
