"""Declarative fault injection: the chaos plane's plan language.

A :class:`FaultPlan` is a list of typed fault components over a run of
``rounds`` rounds. :meth:`FaultPlan.compile` lowers it to per-round
numpy arrays — the exact shapes the engines thread through their scan
bodies — and :func:`apply_plan` merges those arrays into a
``sim.engine.Schedule`` so every engine consumes faults through the one
schedule object it already takes. A plan with no components compiles to
``None`` arrays everywhere, which keeps the engines' static zero-cost
skip: fault-free runs trace bit-identically to the pre-chaos kernels.

Component kinds (all windows are ``[start, stop)`` in rounds):

- ``loss``: receiver-side message loss with probability ``prob`` for
  the listed receiver ``regions`` (empty = every region). Composes with
  a config's ambient ``loss_prob`` as independent processes
  (ops/faulting.apply_loss).
- ``partition``: link cut between region sides ``a`` and ``b`` (``b``
  empty = every region not in ``a``). ``one_way=True`` cuts only the
  a→b direction — ``b`` stops hearing ``a`` while a keeps hearing b —
  the asymmetric-partition case a symmetric mask can't express.
- ``flap``: a partition that toggles every ``period`` rounds inside its
  window (first half-cycle: cut) — the flapping-WAN-link scenario.
- ``churn``: kill ``nodes`` at ``start``; revive them at ``revive_at``
  (``None`` = never — such a plan does not heal). ``wipe=True`` makes
  the kill a crash-with-state-wipe (restart from empty replica state,
  ops/faulting.wipe_nodes) instead of the default pause-resume.
  NOTE: the sparse engine degrades wipe to pause-resume — its bounded
  deviation tables cannot represent a node that lags on EVERY cold
  writer — and sim/invariants.py records that degradation in its
  report facts.
- ``probe_loss``: drops SWIM probe/ack exchanges only (``prob``),
  leaving the data plane untouched — membership stress in isolation.
- ``preempt``: hard-kills one DEVICE shard of the kernel state at round
  ``start`` (no graceful drain, mirroring ``Agent.abort`` crash
  semantics). This is a host/elastic-plane axis: :meth:`FaultPlan.compile`
  does NOT lower it to kernel arrays — the elastic survival driver
  (``corrosion_tpu/elastic``) consumes it via
  :meth:`FaultPlan.preempt_events` and must recover the lost shard from
  the last checkpoint + gap replay. A preempt plan run without the
  elastic driver is a harness bug, which the machinery-fired rule
  (recovery counters staying at zero) catches.

Everything here is host-side numpy; the arrays become device inputs
inside the engines. JSON round-trip (``to_json``/``from_json``) is the
chaos fuzzer's repro-artifact format (docs/CHAOS.md).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, replace

import numpy as np

PLAN_SCHEMA = "corro-fault-plan/1"

# Kernel kinds lower to per-round schedule arrays; "preempt" is the
# elastic plane's device-shard axis and never reaches the scan bodies.
KERNEL_KINDS = ("loss", "partition", "flap", "churn", "probe_loss")
KINDS = KERNEL_KINDS + ("preempt",)


@dataclass(frozen=True)
class Fault:
    """One fault component. Only the fields its ``kind`` reads matter;
    the rest keep their defaults (and serialize compactly)."""

    kind: str
    start: int
    stop: int  # exclusive
    prob: float = 0.0  # loss / probe_loss
    regions: tuple = ()  # loss: receiver regions (() = all)
    a: tuple = ()  # partition/flap: side A region ids
    b: tuple = ()  # partition/flap: side B (() = all regions not in a)
    one_way: bool = False  # cut a->b only (b stops hearing a)
    period: int = 0  # flap: rounds per on/off half-cycle
    nodes: tuple = ()  # churn victims
    revive_at: int | None = None  # churn (None = never revived)
    wipe: bool = False  # churn: crash-with-state-wipe
    device: int = -1  # preempt: device shard index to hard-kill

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; one of {KINDS}")
        if not (0 <= self.start < self.stop):
            raise ValueError(
                f"{self.kind}: need 0 <= start < stop, got "
                f"[{self.start}, {self.stop})"
            )
        if self.kind in ("loss", "probe_loss") and not (0.0 < self.prob <= 1.0):
            raise ValueError(f"{self.kind}: prob must be in (0, 1], got {self.prob}")
        if self.kind in ("partition", "flap") and not self.a:
            raise ValueError(f"{self.kind}: side `a` must name >= 1 region")
        if self.kind == "flap" and self.period <= 0:
            raise ValueError("flap: period must be >= 1 round")
        if self.kind == "churn":
            if not self.nodes:
                raise ValueError("churn: needs >= 1 victim node")
            if self.revive_at is not None and self.revive_at <= self.start:
                raise ValueError(
                    f"churn: revive_at {self.revive_at} must be after the "
                    f"kill round {self.start}"
                )
        if self.wipe and self.kind != "churn":
            raise ValueError("wipe is a churn-only flag")
        if self.kind == "preempt":
            if self.device < 0:
                raise ValueError("preempt: needs a device shard index >= 0")
            if self.stop != self.start + 1:
                raise ValueError(
                    "preempt is instantaneous: stop must be start + 1, "
                    f"got [{self.start}, {self.stop})"
                )
        elif self.device >= 0:
            raise ValueError("device is a preempt-only field")

    @property
    def clears_at(self) -> int | None:
        """First round with this component fully healed, None = never."""
        if self.kind == "churn":
            return None if self.revive_at is None else self.revive_at + 1
        return self.stop

    def to_dict(self) -> dict:
        d: dict = {"kind": self.kind, "start": self.start, "stop": self.stop}
        if self.kind in ("loss", "probe_loss"):
            d["prob"] = self.prob
        if self.kind == "loss" and self.regions:
            d["regions"] = list(self.regions)
        if self.kind in ("partition", "flap"):
            d["a"] = list(self.a)
            if self.b:
                d["b"] = list(self.b)
            if self.one_way:
                d["one_way"] = True
        if self.kind == "flap":
            d["period"] = self.period
        if self.kind == "churn":
            d["nodes"] = list(self.nodes)
            d["revive_at"] = self.revive_at
            if self.wipe:
                d["wipe"] = True
        if self.kind == "preempt":
            d["device"] = self.device
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Fault":
        return cls(
            kind=d["kind"], start=int(d["start"]), stop=int(d["stop"]),
            prob=float(d.get("prob", 0.0)),
            regions=tuple(d.get("regions", ())),
            a=tuple(d.get("a", ())), b=tuple(d.get("b", ())),
            one_way=bool(d.get("one_way", False)),
            period=int(d.get("period", 0)),
            nodes=tuple(d.get("nodes", ())),
            revive_at=(
                None if d.get("revive_at") is None else int(d["revive_at"])
            ),
            wipe=bool(d.get("wipe", False)),
            device=int(d.get("device", -1)),
        )


@dataclass
class CompiledFaults:
    """FaultPlan lowered to the per-round arrays the engines thread.
    ``None`` means that fault axis is absent — the trace-time flag the
    engines' static zero-cost skip keys on."""

    rounds: int
    loss: np.ndarray | None = None  # f32[rounds, R] receiver-region loss
    probe_loss: np.ndarray | None = None  # f32[rounds]
    partition: np.ndarray | None = None  # bool[rounds, R, R] directional
    kill: np.ndarray | None = None  # bool[rounds, N]
    revive: np.ndarray | None = None  # bool[rounds, N]
    wipe: np.ndarray | None = None  # bool[rounds, N] (subset of kill)
    heal_round: int = 0  # first round with every fault cleared
    heals: bool = True  # False: some component never clears

    @property
    def loss_scalar(self) -> np.ndarray | None:
        """f32[rounds] worst-region loss — the no-region chunk plane's
        view of the loss schedule."""
        return None if self.loss is None else self.loss.max(axis=1)

    def alive_curve(self, n_nodes: int) -> np.ndarray:
        """bool[rounds, N] ground-truth liveness per round (kill/revive
        folded cumulatively) — for engines without a SWIM plane."""
        alive = np.ones((self.rounds, n_nodes), bool)
        cur = np.ones(n_nodes, bool)
        for r in range(self.rounds):
            if self.kill is not None:
                cur &= ~self.kill[r]
            if self.revive is not None:
                cur |= self.revive[r]
            alive[r] = cur
        return alive


@dataclass(frozen=True)
class FaultPlan:
    rounds: int
    faults: tuple = ()
    name: str = ""

    def __post_init__(self):
        if self.rounds <= 0:
            raise ValueError("plan needs rounds >= 1")
        for f in self.faults:
            if f.stop > self.rounds and f.kind != "churn":
                raise ValueError(
                    f"{f.kind} window [{f.start}, {f.stop}) exceeds the "
                    f"plan's {self.rounds} rounds"
                )
            if f.start >= self.rounds:
                raise ValueError(
                    f"{f.kind} starts at {f.start}, past the plan's "
                    f"{self.rounds} rounds"
                )
            if (
                f.kind == "churn"
                and f.revive_at is not None
                and f.revive_at >= self.rounds
            ):
                raise ValueError(
                    f"churn revive_at {f.revive_at} is past the plan's "
                    f"{self.rounds} rounds"
                )

    @property
    def is_free(self) -> bool:
        return not self.faults

    @property
    def heals(self) -> bool:
        return all(f.clears_at is not None for f in self.faults)

    @property
    def heal_round(self) -> int:
        """First round with every fault cleared (= the plan's ``rounds``
        when some component never clears)."""
        h = 0
        for f in self.faults:
            h = max(h, self.rounds if f.clears_at is None else f.clears_at)
        return min(h, self.rounds)

    def max_region(self) -> int:
        """Highest region id any component references (-1 = none) — the
        minimum region count the plan needs to compile."""
        m = -1
        for f in self.faults:
            for r in tuple(f.regions) + tuple(f.a) + tuple(f.b):
                m = max(m, int(r))
        return m

    def wipes(self) -> tuple:
        """Node ids any component crash-wipes (invariant bookkeeping)."""
        out: set = set()
        for f in self.faults:
            if f.kind == "churn" and f.wipe:
                out.update(f.nodes)
        return tuple(sorted(out))

    def killed_forever(self) -> tuple:
        out: set = set()
        for f in self.faults:
            if f.kind == "churn" and f.revive_at is None:
                out.update(f.nodes)
        return tuple(sorted(out))

    def preempt_events(self) -> tuple:
        """Device-shard preemptions as sorted ``(round, device)`` pairs —
        the elastic driver's worklist. The kernel compile skips these;
        if this is non-empty the run MUST go through
        ``corrosion_tpu.elastic`` so recovery machinery fires."""
        return tuple(sorted(
            (f.start, f.device) for f in self.faults if f.kind == "preempt"
        ))

    def kernel_plan(self) -> "FaultPlan":
        """The plan with elastic-plane (preempt) components stripped —
        what actually lowers onto the scan bodies."""
        kernel = tuple(f for f in self.faults if f.kind != "preempt")
        if len(kernel) == len(self.faults):
            return self
        return FaultPlan(self.rounds, kernel, self.name)

    # -- lowering -----------------------------------------------------------

    def compile(
        self, n_nodes: int, n_regions: int, allow_wipe: bool = True
    ) -> CompiledFaults:
        """Lower to per-round arrays. ``allow_wipe=False`` degrades wipe
        churn to pause-resume (the sparse engine's bounded-table
        limitation; see the module docstring)."""
        c = CompiledFaults(
            rounds=self.rounds, heal_round=self.heal_round, heals=self.heals
        )
        for f in self.faults:
            stop = min(f.stop, self.rounds)
            if f.kind == "preempt":
                # Elastic-plane axis: consumed by the survival driver via
                # preempt_events(), never lowered to kernel arrays.
                continue
            if f.kind == "loss":
                if c.loss is None:
                    c.loss = np.zeros((self.rounds, n_regions), np.float32)
                regions = f.regions or tuple(range(n_regions))
                for r in regions:
                    if not (0 <= r < n_regions):
                        raise ValueError(f"loss region {r} out of range")
                    c.loss[f.start:stop, r] = np.maximum(
                        c.loss[f.start:stop, r], np.float32(f.prob)
                    )
            elif f.kind == "probe_loss":
                if c.probe_loss is None:
                    c.probe_loss = np.zeros(self.rounds, np.float32)
                c.probe_loss[f.start:stop] = np.maximum(
                    c.probe_loss[f.start:stop], np.float32(f.prob)
                )
            elif f.kind in ("partition", "flap"):
                if c.partition is None:
                    c.partition = np.zeros(
                        (self.rounds, n_regions, n_regions), bool
                    )
                side_a = list(f.a)
                side_b = list(f.b) or [
                    r for r in range(n_regions) if r not in f.a
                ]
                for r in side_a + side_b:
                    if not (0 <= r < n_regions):
                        raise ValueError(f"partition region {r} out of range")
                for t in range(f.start, stop):
                    if f.kind == "flap" and (
                        ((t - f.start) // f.period) % 2 == 1
                    ):
                        continue  # off half-cycle: link up
                    for ra in side_a:
                        for rb in side_b:
                            if ra == rb:
                                continue
                            # partition[receiver, source]: b can't hear a.
                            c.partition[t, rb, ra] = True
                            if not f.one_way:
                                c.partition[t, ra, rb] = True
            elif f.kind == "churn":
                if c.kill is None:
                    c.kill = np.zeros((self.rounds, n_nodes), bool)
                    c.revive = np.zeros((self.rounds, n_nodes), bool)
                nodes = np.asarray(f.nodes, np.int64)
                if nodes.min() < 0 or nodes.max() >= n_nodes:
                    raise ValueError(f"churn node out of range: {f.nodes}")
                c.kill[f.start, nodes] = True
                if f.revive_at is not None:
                    c.revive[f.revive_at, nodes] = True
                if f.wipe and allow_wipe:
                    if c.wipe is None:
                        c.wipe = np.zeros((self.rounds, n_nodes), bool)
                    c.wipe[f.start, nodes] = True
        return c

    # -- serialization ------------------------------------------------------

    def to_dict(self) -> dict:
        d = {
            "schema": PLAN_SCHEMA,
            "rounds": self.rounds,
            "faults": [f.to_dict() for f in self.faults],
        }
        if self.name:
            d["name"] = self.name
        return d

    def to_json(self, indent: int | None = None) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        if d.get("schema", PLAN_SCHEMA) != PLAN_SCHEMA:
            raise ValueError(f"not a {PLAN_SCHEMA} plan: {d.get('schema')}")
        return cls(
            rounds=int(d["rounds"]),
            faults=tuple(Fault.from_dict(f) for f in d.get("faults", ())),
            name=str(d.get("name", "")),
        )

    @classmethod
    def from_json(cls, s: str) -> "FaultPlan":
        return cls.from_dict(json.loads(s))

    def describe(self) -> str:
        if not self.faults:
            return f"fault-free ({self.rounds} rounds)"
        parts = []
        for f in self.faults:
            if f.kind == "loss":
                where = f"regions {list(f.regions)}" if f.regions else "all"
                parts.append(
                    f"loss p={f.prob:g} {where} [{f.start},{f.stop})"
                )
            elif f.kind == "probe_loss":
                parts.append(f"probe_loss p={f.prob:g} [{f.start},{f.stop})")
            elif f.kind in ("partition", "flap"):
                arrow = "->" if f.one_way else "<->"
                b = list(f.b) if f.b else "rest"
                extra = f" period={f.period}" if f.kind == "flap" else ""
                parts.append(
                    f"{f.kind} {list(f.a)}{arrow}{b}{extra} "
                    f"[{f.start},{f.stop})"
                )
            elif f.kind == "preempt":
                parts.append(f"preempt device {f.device} @{f.start}")
            else:
                w = "wipe" if f.wipe else "pause"
                rv = "never" if f.revive_at is None else f.revive_at
                parts.append(
                    f"churn {len(f.nodes)} nodes ({w}) kill@{f.start} "
                    f"revive@{rv}"
                )
        heal = (
            f"heals@{self.heal_round}" if self.heals else "NEVER HEALS"
        )
        return "; ".join(parts) + f" | {heal}/{self.rounds} rounds"


# ---------------------------------------------------------------------------
# Named scenarios — the curated chaos catalog (docs/CHAOS.md).


def named_scenarios(
    rounds: int, n_regions: int, n_nodes: int, protect: tuple = ()
) -> dict:
    """The curated fault catalog at a given cluster shape. ``protect``
    lists node ids churn must not touch (writer/origin nodes — the
    durability invariant is stated for surviving writers)."""
    if n_regions < 2 or rounds < 24:
        raise ValueError("scenarios need >= 2 regions and >= 24 rounds")
    f0, f1 = rounds // 6, rounds // 2  # fault window; the rest drains
    victims = tuple(
        n for n in range(n_nodes) if n not in set(protect)
    )[: max(2, n_nodes // 16)]
    revive = (f0 + f1) // 2
    plans = {
        "partition-heal": FaultPlan(rounds, (
            Fault("partition", f0, f1, a=(0,)),
        ), name="partition-heal"),
        "oneway-blackout": FaultPlan(rounds, (
            Fault("partition", f0, f1, a=(0,), one_way=True),
        ), name="oneway-blackout"),
        "flaky-link": FaultPlan(rounds, (
            Fault("flap", f0, f1, a=(0,), b=(1,), period=3),
        ), name="flaky-link"),
        "loss-burst": FaultPlan(rounds, (
            Fault("loss", f0, f1, prob=0.4),
        ), name="loss-burst"),
        "region-brownout": FaultPlan(rounds, (
            Fault("loss", f0, f1, prob=0.7, regions=(0,)),
        ), name="region-brownout"),
        "probe-storm": FaultPlan(rounds, (
            Fault("probe_loss", f0, f1, prob=0.6),
        ), name="probe-storm"),
        "crash-pause": FaultPlan(rounds, (
            Fault("churn", f0, f0 + 1, nodes=victims, revive_at=revive),
        ), name="crash-pause"),
        "crash-wipe": FaultPlan(rounds, (
            Fault("churn", f0, f0 + 1, nodes=victims, revive_at=revive,
                  wipe=True),
        ), name="crash-wipe"),
        "kitchen-sink": FaultPlan(rounds, (
            Fault("loss", f0, f1, prob=0.25),
            Fault("partition", f0 + 2, f1 - 2, a=(0,), one_way=True),
            Fault("churn", f0 + 1, f0 + 2, nodes=victims[:2],
                  revive_at=revive, wipe=True),
            Fault("probe_loss", f0, f1, prob=0.3),
        ), name="kitchen-sink"),
    }
    return plans


# ---------------------------------------------------------------------------
# Random plan generation + shrinking — the chaos fuzzer's core.


def random_plan(
    rng: np.random.Generator,
    rounds: int,
    n_regions: int,
    n_nodes: int,
    protect: tuple = (),
    max_faults: int = 3,
    allow_wipe: bool = True,
    break_heal: bool = False,
) -> FaultPlan:
    """Sample a healing fault plan: every component clears by ~5/8 of the
    run so the drain tail can prove recovery. ``break_heal=True``
    deliberately generates a NON-healing plan (a partition held to the
    final round) — the invariant suite must fail on it, and the shrinker
    must reduce it to a minimal repro (the chaos plane's self-test)."""
    heal_by = max(rounds * 5 // 8, 8)
    eligible = [n for n in range(n_nodes) if n not in set(protect)]
    faults: list[Fault] = []
    n_faults = int(rng.integers(1, max_faults + 1))
    # Fuzz over kernel kinds only: preempt needs the elastic driver's
    # recovery path and would be a silent no-op under plain simulate().
    kinds = list(KERNEL_KINDS)
    for _ in range(n_faults):
        kind = kinds[int(rng.integers(0, len(kinds)))]
        start = int(rng.integers(2, max(heal_by // 2, 3)))
        stop = int(rng.integers(start + 2, heal_by + 1))
        if kind == "loss":
            all_regions = rng.random() < 0.5
            regions = (
                () if all_regions
                else tuple(
                    int(r) for r in rng.choice(
                        n_regions, size=max(1, n_regions // 2),
                        replace=False,
                    )
                )
            )
            faults.append(Fault(
                "loss", start, stop,
                prob=float(rng.uniform(0.2, 0.6)), regions=regions,
            ))
        elif kind == "probe_loss":
            faults.append(Fault(
                "probe_loss", start, stop,
                prob=float(rng.uniform(0.3, 0.7)),
            ))
        elif kind in ("partition", "flap"):
            a = (int(rng.integers(0, n_regions)),)
            rest = [r for r in range(n_regions) if r != a[0]]
            b = (
                () if rng.random() < 0.5
                else (int(rng.choice(rest)),)
            )
            if kind == "flap":
                faults.append(Fault(
                    "flap", start, stop, a=a, b=b,
                    period=int(rng.integers(2, 5)),
                ))
            else:
                faults.append(Fault(
                    "partition", start, stop, a=a, b=b,
                    one_way=bool(rng.random() < 0.5),
                ))
        else:  # churn
            if not eligible:
                continue
            k = int(rng.integers(1, max(2, len(eligible) // 8)))
            nodes = tuple(
                int(x) for x in rng.choice(eligible, size=k, replace=False)
            )
            revive_at = int(rng.integers(start + 3, heal_by + 1))
            faults.append(Fault(
                "churn", start, start + 1, nodes=nodes,
                revive_at=min(revive_at, rounds - 1),
                wipe=bool(allow_wipe and rng.random() < 0.5),
            ))
    if break_heal or not faults:
        # A partition that never clears: the canonical non-healing fault.
        faults.append(Fault(
            "partition", max(rounds // 4, 1), rounds, a=(0,),
        ))
    return FaultPlan(rounds=rounds, faults=tuple(faults))


def shrink_plan(plan: FaultPlan, still_fails, max_evals: int = 32):
    """Reduce a failing plan to a minimal repro: greedily drop whole
    components, then bisect each survivor's round window (and halve
    churn victim sets), as long as the reduced plan ``still_fails``.
    Returns ``(minimal_plan, evals_used)``."""
    evals = 0

    def check(p: FaultPlan) -> bool:
        nonlocal evals
        if evals >= max_evals:
            return False
        evals += 1
        return bool(still_fails(p))

    # Pass 1: greedy component drop (reverse order: later components are
    # more likely incidental riders on the failing window).
    faults = list(plan.faults)
    i = len(faults) - 1
    while i >= 0 and len(faults) > 1:
        cand = FaultPlan(
            plan.rounds, tuple(faults[:i] + faults[i + 1:]), plan.name
        )
        if check(cand):
            faults = list(cand.faults)
        i -= 1
    plan = FaultPlan(plan.rounds, tuple(faults), plan.name)

    # Pass 2: per-component window bisection / victim halving.
    changed = True
    while changed and evals < max_evals:
        changed = False
        for i, f in enumerate(plan.faults):
            cands: list[Fault] = []
            width = f.stop - f.start
            if width > 1 and f.kind != "churn":
                mid = f.start + width // 2
                cands.append(replace(f, stop=mid))
                cands.append(replace(f, start=mid))
            if f.kind == "churn" and len(f.nodes) > 1:
                half = len(f.nodes) // 2
                cands.append(replace(f, nodes=f.nodes[:half]))
                cands.append(replace(f, nodes=f.nodes[half:]))
            for cf in cands:
                cand = FaultPlan(
                    plan.rounds,
                    plan.faults[:i] + (cf,) + plan.faults[i + 1:],
                    plan.name,
                )
                if check(cand):
                    plan = cand
                    changed = True
                    break
            if changed:
                break
    return plan, evals


# ---------------------------------------------------------------------------
# Constant-rate axes — the fidelity plane's model→axes compiler entry.


def axes_from_rates(
    rounds: int,
    loss_by_region=None,
    probe_loss: float = 0.0,
    eps: float = 1e-9,
) -> CompiledFaults:
    """Lower constant per-round rates to :class:`CompiledFaults` — the
    entry the fidelity plane's calibrated :class:`RoundModel` compiles
    through (``fidelity/calibrate.py``), so calibration data flows into
    the engines via the chaos plane's already-tested axes instead of any
    new traced code.

    ``loss_by_region`` is a length-R array of receiver-region
    delivery-miss probabilities (a message whose wall-clock latency
    straddles the round boundary misses this round's flush and is
    recovered by rebroadcast/anti-entropy — exactly the loss axis's
    semantics), or a [rounds, R] matrix when the rate varies per round
    (the fidelity model's apply-backlog term under bursts);
    ``probe_loss`` is the SWIM probe-plane loss derived from probe
    timeout tails. Rates at or below ``eps`` compile to ABSENT axes
    (``None``), preserving the engines' static zero-cost fault-free
    skip: the identity model's schedule is bit-identical to no model at
    all. Deterministic: equal inputs compile to bit-identical arrays.
    """
    if rounds <= 0:
        raise ValueError(f"rounds must be >= 1, got {rounds}")
    c = CompiledFaults(rounds=rounds, heal_round=0, heals=True)
    if loss_by_region is not None:
        arr = np.asarray(loss_by_region, np.float32)
        if arr.ndim == 2 and arr.shape[0] != rounds:
            raise ValueError(
                f"per-round loss_by_region must have {rounds} rows, got "
                f"shape {arr.shape}"
            )
        if arr.ndim not in (1, 2):
            raise ValueError(
                f"loss_by_region must be [regions] or [rounds, regions], "
                f"got shape {arr.shape}"
            )
        if arr.size and (arr.min() < 0.0 or arr.max() > 1.0):
            raise ValueError(
                f"loss_by_region probabilities must be in [0, 1]: {arr}"
            )
        if arr.size and float(arr.max()) > eps:
            c.loss = (
                np.repeat(arr[None, :], rounds, axis=0)
                if arr.ndim == 1 else arr.copy()
            )
    if not 0.0 <= probe_loss <= 1.0:
        raise ValueError(f"probe_loss must be in [0, 1], got {probe_loss}")
    if probe_loss > eps:
        c.probe_loss = np.full(rounds, np.float32(probe_loss), np.float32)
    return c


# ---------------------------------------------------------------------------
# Schedule integration.


def apply_plan(schedule, plan, n_nodes: int, n_regions: int,
               allow_wipe: bool = True):
    """Merge a FaultPlan (or CompiledFaults) into a ``sim.engine.Schedule``:
    churn masks OR with the schedule's own, partitions OR, and the
    loss/probe_loss/wipe axes attach. Returns a new Schedule; the input
    is not mutated."""
    from corrosion_tpu.sim.engine import Schedule

    c = (
        plan.compile(n_nodes, n_regions, allow_wipe=allow_wipe)
        if isinstance(plan, FaultPlan) else plan
    )
    if c.rounds != schedule.rounds:
        raise ValueError(
            f"plan rounds {c.rounds} != schedule rounds {schedule.rounds}"
        )

    def _or(a, b):
        if a is None:
            return None if b is None else b.copy()
        if b is None:
            return a.copy()
        return a | b

    partition = schedule.partition
    if c.partition is not None:
        if partition is None:
            partition = c.partition.copy()
        elif partition.shape != c.partition.shape:
            raise ValueError(
                f"partition shape {c.partition.shape} != schedule's "
                f"{partition.shape} (region count mismatch?)"
            )
        else:
            partition = partition | c.partition
    return Schedule(
        writes=schedule.writes,
        kill=_or(schedule.kill, c.kill),
        revive=_or(schedule.revive, c.revive),
        partition=partition,
        sample_writer=schedule.sample_writer,
        sample_ver=schedule.sample_ver,
        sample_round=schedule.sample_round,
        loss=_max_merge(schedule.loss, c.loss),
        probe_loss=_max_merge(schedule.probe_loss, c.probe_loss),
        wipe=_or(schedule.wipe, c.wipe),
    )


def _max_merge(a, b):
    if a is None:
        return None if b is None else b.copy()
    if b is None:
        return a.copy()
    return np.maximum(a, b)
