"""Convergence health analyzer: flight records → protocol verdicts.

The on-device convergence health plane (sim/telemetry.py
``HEALTH_CURVE_KEYS``, emitted by every engine's scan body) measures the
quantities the simulator exists to report — staleness lag, delivery
latency, SWIM misbelief, backlog mass — per round. This module is the
host side: it consumes those curves (in memory, or replayed from a
flight-recorder JSONL) and derives the run-level verdicts:

- **time-to-convergence**: the first round after which need, membership
  mismatches, and staleness stay zero to the end of the record;
- **staleness percentiles**: p50/p99 of the per-round cluster staleness
  mass plus the peak single-node lag;
- **delivery-latency CDF**: cumulative distribution over the fixed
  on-device histogram buckets (``VIS_LAT_EDGES``), with bucket-resolution
  p50/p99 — derived from the flight record alone, no final state needed;
- **per-churn-event detection latency**: excursions of the
  ``swim_undetected_deaths`` curve above zero segment the record into
  kill events and their rounds-to-detection.

``publish_report`` folds the derived verdicts into a MetricsRegistry as
``corro_kernel_health_*`` gauges (the per-round curves themselves are
published by ``telemetry.publish_curves`` under the same prefix), and
``diff_reports`` flags regressions between two runs with BENCH-style
relative tolerances — the `obs diff` CLI backend.
"""

from __future__ import annotations

import json
import math
import os
import time
from dataclasses import asdict, dataclass, field

import numpy as np

from corrosion_tpu.sim.telemetry import (
    VIS_LAT_EDGES,
    VIS_LAT_KEYS,
    curve_array,
    replay_flight,
)

REPORT_SCHEMA = "corro-convergence-report/1"


def flight_header(path: str) -> dict:
    """First ``{"kind": "flight", ...}`` record of a flight JSONL (the
    engine + open timestamp), or {} for a headerless/garbage file."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if obj.get("kind") == "flight":
                return obj
            return {}
    return {}


def iter_flight(path: str, follow: bool = False, poll_s: float = 0.25,
                idle_timeout_s: float | None = None):
    """Yield parsed records from a flight JSONL, optionally tailing a
    file that is still being written.

    Only whole lines are consumed: a partially-flushed tail line is held
    back until its newline arrives (``follow=True``) or skipped at EOF
    (``follow=False``). Garbage lines (a crash's torn write) are
    skipped, like ``replay_flight``. ``idle_timeout_s`` bounds how long
    a follow waits without new data before giving up (None = forever).

    **Rotation-aware** (``follow=True``): the size-capped recorder
    renames the live file to ``path.N`` and opens a fresh ``path``
    (``FlightRecorder.max_bytes``) — a follower holding the old handle
    would silently stop seeing records. At EOF the live file's inode is
    re-checked; on a rotation the old handle is drained to completion
    (the renamed file keeps serving its fd), then the follower replays
    any gap through the rotated segment chain (segment headers carry
    their index; header ``S`` lives at ``path.{S+1}`` once rotated —
    the ``flight_segments`` naming contract) before resuming on the
    live file. No record is lost or re-read across any number of
    rotations between polls.
    """
    cur_path = path
    cur_seg: int | None = None
    opened_any = False
    idle = 0.0
    while True:
        try:
            f = open(cur_path)
        except FileNotFoundError:
            if not opened_any:
                raise  # a missing/typo'd path is an error, not an empty tail
            # Mid-rotation race: the live path is briefly absent between
            # os.replace and the fresh open. Poll, don't die.
            if not follow:
                return
            if idle_timeout_s is not None and idle >= idle_timeout_s:
                return
            time.sleep(poll_s)
            idle += poll_s
            continue
        opened_any = True
        redirect = False  # gap-detection already chose the next file
        with f:
            ino = os.fstat(f.fileno()).st_ino
            buf = ""
            while True:
                chunk = f.readline()
                if chunk:
                    buf += chunk
                    if not buf.endswith("\n"):
                        continue  # partial line: wait for the rest
                    line, buf = buf.strip(), ""
                    idle = 0.0
                    if not line:
                        continue
                    try:
                        obj = json.loads(line)
                    except ValueError:
                        continue
                    if obj.get("kind") == "flight" and "segment" in obj:
                        seg = int(obj["segment"])
                        if cur_seg is not None and seg > cur_seg + 1:
                            # This file starts PAST the next unread
                            # segment — the recorder rotated between
                            # our exists() probe and the open (the
                            # check-then-open race). Replay the missed
                            # segment(s) first; this file is revisited
                            # through the normal chain advance, from
                            # the top, nothing yielded from this visit.
                            missed = f"{path}.{cur_seg + 2}"
                            if os.path.exists(missed):
                                cur_path = missed
                                redirect = True
                                break
                        cur_seg = seg
                    yield obj
                    continue
                # EOF on the current handle.
                if not follow:
                    return
                if cur_path != path:
                    break  # finished replaying a rotated segment
                try:
                    rotated = os.stat(path).st_ino != ino
                except FileNotFoundError:
                    rotated = True
                if rotated:
                    break  # old live file fully drained — advance
                if idle_timeout_s is not None and idle >= idle_timeout_s:
                    return
                time.sleep(poll_s)
                idle += poll_s
        # Advance along the segment chain: the file just drained carried
        # header segment ``cur_seg`` (rotated name path.{cur_seg+1}), so
        # the next unread segment's header is cur_seg+1 — at
        # path.{cur_seg+2} when it too already rotated, else the live
        # file (whose header is re-checked on open: if it rotated again
        # between this probe and the open, the gap detection above
        # redirects to the missed segment without yielding anything).
        if not redirect:
            nxt = None
            if cur_seg is not None:
                cand = f"{path}.{cur_seg + 2}"
                if os.path.exists(cand):
                    nxt = cand
            cur_path = nxt if nxt is not None else path


# Shared zero-fill curve accessor (telemetry.curve_array): old flight
# files predating a plane replay as all-zero for its keys.
_arr = curve_array


def detection_latencies(undetected: np.ndarray,
                        kill_rounds=None) -> list[dict]:
    """Per-churn-event rounds-to-detection from the
    ``swim_undetected_deaths`` curve.

    Without ``kill_rounds``: each excursion of the curve above zero is
    one (possibly merged) churn event; its detection latency is the
    excursion length in rounds, ``None`` while still unresolved at the
    end of the record. With ``kill_rounds`` (the schedule's ground
    truth): one event per kill round, detected at the first later round
    where the curve returns to zero — overlapping kills then get their
    own per-event latencies instead of one merged excursion.

    Caveat: the curve counts (live observer, DEAD target) misbeliefs, so
    a victim's REVIVAL vacuously clears its pairs — the reported latency
    is "rounds until no live observer believed a dead node up", an upper
    bound clipped at the kill→revive gap when SWIM had not finished
    declaring the death by then. Schedules meant to measure pure
    detection speed should revive well after ``suspect_rounds`` plus
    dissemination time (churned_demo_cluster's rounds//4 → rounds//2
    spacing leaves ~rounds/4 rounds, ample for the default config).
    """
    u = np.asarray(undetected, dtype=np.float64)
    events: list[dict] = []
    if kill_rounds is not None:
        for k in kill_rounds:
            k = int(k)
            after = np.nonzero((np.arange(len(u)) >= k) & (u == 0))[0]
            events.append({
                "round": k,
                "detected_rounds": (
                    int(after[0] - k) if after.size else None
                ),
            })
        return events
    above = u > 0
    start = None
    for r, a in enumerate(above):
        if a and start is None:
            start = r
        elif not a and start is not None:
            events.append({"round": start, "detected_rounds": r - start})
            start = None
    if start is not None:
        events.append({"round": start, "detected_rounds": None})
    return events


def recovery_after_heal(
    curves: dict, heal_round: int, round_ms: float = 500.0,
    require_membership: bool = False,
) -> dict:
    """Recovery-time-after-heal: how long the protocol took to go quiet
    once the last injected fault cleared (the chaos plane's headline
    verdict, consumed by sim/invariants.py).

    Quiet means ``need == 0``, ``staleness_sum == 0``, and
    ``swim_undetected_deaths == 0`` sustained to the end of the record.
    ``mismatches`` joins the predicate only with
    ``require_membership=True``: suspect/down beliefs about LIVE nodes
    are sticky by design until down-GC forgets them (the reference's
    ``remove_down_after`` is 48 h), so a probe-loss storm legitimately
    leaves nonzero mismatches long after the data plane recovered.

    Returns ``{"heal_round", "recovered_round", "recovery_rounds",
    "recovery_s"}`` with Nones when the record never recovers.
    """
    need = _arr(curves, "need")
    rounds = len(need)

    def _get(key):
        # Zero-fill anchored on the need curve: partial dicts (tests,
        # pre-health flight replays) must not break the broadcast.
        if key in curves:
            return np.asarray(curves[key], dtype=np.float64)
        return np.zeros(rounds, dtype=np.float64)

    stale = _get("staleness_sum")
    undet = _get("swim_undetected_deaths")
    quiet = (need == 0) & (stale == 0) & (undet == 0)
    if require_membership:
        quiet &= _get("mismatches") == 0
    recovered: int | None = None
    if rounds and quiet[-1]:
        nonquiet = np.nonzero(~quiet)[0]
        recovered = int(nonquiet[-1]) + 1 if nonquiet.size else 0
        recovered = max(recovered, int(heal_round))
    rec_rounds = None if recovered is None else recovered - int(heal_round)
    return {
        "heal_round": int(heal_round),
        "recovered_round": recovered,
        "recovery_rounds": rec_rounds,
        "recovery_s": (
            None if rec_rounds is None
            else rec_rounds * round_ms / 1000.0
        ),
    }


def cdf_quantile(counts: np.ndarray, q: float) -> tuple[int, float]:
    """(bucket index, upper edge in rounds) of quantile ``q`` over the
    fixed delivery-latency buckets; the overflow bucket's edge is inf.
    Returns (-1, nan) when the histogram is empty."""
    counts = np.asarray(counts, dtype=np.float64)
    total = counts.sum()
    if total <= 0:
        return -1, float("nan")
    cdf = np.cumsum(counts) / total
    idx = int(np.searchsorted(cdf, q, side="left"))
    idx = min(idx, len(counts) - 1)
    edge = (
        float(VIS_LAT_EDGES[idx]) if idx < len(VIS_LAT_EDGES)
        else float("inf")
    )
    return idx, edge


def latency_bucket(lat_rounds: float) -> int:
    """Bucket index a latency (in rounds) lands in — the host-side twin
    of the on-device bucketize, for agreement checks."""
    idx = 0
    for e in VIS_LAT_EDGES:
        if lat_rounds > e:
            idx += 1
    return idx


@dataclass
class ConvergenceReport:
    """Run-level protocol-health verdicts derived from round curves."""

    engine: str = "unknown"
    rounds: int = 0
    round_ms: float = 500.0
    # Convergence
    converged_round: int | None = None  # first all-quiet round
    ttc_s: float | None = None  # converged_round in simulated seconds
    need_last: float = 0.0
    mismatches_last: float = 0.0
    staleness_last: float = 0.0
    # Staleness over the run
    staleness_p50: float = float("nan")
    staleness_p99: float = float("nan")
    staleness_max_peak: float = 0.0
    # Delivery latency (from the on-device histogram alone)
    vis_total: int = 0
    vis_hist: list = field(default_factory=list)  # counts per bucket
    vis_cdf: list = field(default_factory=list)  # cumulative fractions
    vis_p50_bucket: int = -1
    vis_p99_bucket: int = -1
    vis_p50_s: float = float("nan")  # bucket upper edge, seconds
    vis_p99_s: float = float("nan")
    # SWIM health
    false_alarms_total: float = 0.0
    flaps_total: float = 0.0
    detection_events: list = field(default_factory=list)
    detection_max_rounds: int | None = None
    undetected_unresolved: int = 0  # events still open at record end
    # Backlog
    queue_backlog_peak: float = 0.0
    queue_backlog_last: float = 0.0
    # Traffic totals (context for diffs)
    msgs_total: float = 0.0
    applied_total: float = 0.0
    sessions_total: float = 0.0

    def to_dict(self) -> dict:
        """JSON-safe dict: strict parsers reject NaN/Infinity, so NaN
        (no data) serializes as null and inf (overflow bucket) as the
        string "inf" — ``load_report`` round-trips both."""
        d = {k: _json_num(v) for k, v in asdict(self).items()}
        d["schema"] = REPORT_SCHEMA
        return d

    @property
    def converged(self) -> bool:
        return self.converged_round is not None

    def render(self) -> str:
        """Human-readable report (the `obs report` default output)."""
        rm = self.round_ms / 1000.0

        def s(x):
            if x is None or (isinstance(x, float) and math.isnan(x)):
                return "n/a"
            return f"{x:g}"

        def lat(x):
            """Latency with its own unit: overflow-bucket values render
            as '>edge s' so callers never append another 's'."""
            if x is None or (isinstance(x, float) and math.isnan(x)):
                return "n/a"
            if isinstance(x, float) and math.isinf(x):
                return f">{VIS_LAT_EDGES[-1] * rm:g}s"
            return f"{x:g}s"

        lines = [
            f"engine={self.engine} rounds={self.rounds} "
            f"round_ms={self.round_ms:g}",
            (
                f"converged: yes at round {self.converged_round} "
                f"({self.ttc_s:g}s simulated)"
                if self.converged
                else f"converged: NO (need={s(self.need_last)} "
                f"mismatches={s(self.mismatches_last)} "
                f"staleness={s(self.staleness_last)} at record end)"
            ),
            f"staleness: p50={s(self.staleness_p50)} "
            f"p99={s(self.staleness_p99)} "
            f"worst_node_peak={s(self.staleness_max_peak)} "
            f"last={s(self.staleness_last)}",
        ]
        if self.vis_total:
            marks = [f"{e * rm:g}s" for e in VIS_LAT_EDGES] + ["inf"]
            cdf = " ".join(
                f"<={m}:{c * 100:.1f}%"
                for m, c in zip(marks, self.vis_cdf)
            )
            lines.append(
                f"delivery latency ({self.vis_total} events): "
                f"p50<={lat(self.vis_p50_s)} p99<={lat(self.vis_p99_s)}"
            )
            lines.append(f"  CDF: {cdf}")
        else:
            lines.append("delivery latency: no visibility events recorded")
        det = [
            e["detected_rounds"] for e in self.detection_events
            if e["detected_rounds"] is not None
        ]
        lines.append(
            f"swim: false_alarm_pair_rounds={s(self.false_alarms_total)} "
            f"flaps={s(self.flaps_total)} churn_events="
            f"{len(self.detection_events)} "
            + (
                f"detection_rounds_max={max(det)} " if det else ""
            )
            + f"unresolved={self.undetected_unresolved}"
        )
        lines.append(
            f"backlog: queue_peak={s(self.queue_backlog_peak)} "
            f"queue_last={s(self.queue_backlog_last)}"
        )
        lines.append(
            f"traffic: msgs={s(self.msgs_total)} "
            f"applied={s(self.applied_total)} "
            f"sessions={s(self.sessions_total)}"
        )
        return "\n".join(lines)


def report_from_curves(
    curves: dict,
    engine: str = "unknown",
    round_ms: float = 500.0,
    kill_rounds=None,
) -> ConvergenceReport:
    """Derive a ConvergenceReport from per-round curves (any engine's
    ``round_curves`` output, or a ``replay_flight`` reconstruction)."""
    need = _arr(curves, "need")
    mism = _arr(curves, "mismatches")
    stale = _arr(curves, "staleness_sum")
    rounds = len(need)

    quiet = (need == 0) & (mism == 0) & (stale == 0)
    converged_round: int | None = None
    if rounds and quiet[-1]:
        # First round of the trailing all-quiet run.
        nonquiet = np.nonzero(~quiet)[0]
        converged_round = int(nonquiet[-1]) + 1 if nonquiet.size else 0

    hist = np.asarray(
        [_arr(curves, k).sum() for k in VIS_LAT_KEYS], dtype=np.float64
    )
    total = int(hist.sum())
    cdf = (np.cumsum(hist) / total).tolist() if total else []
    p50_b, p50_edge = cdf_quantile(hist, 0.50)
    p99_b, p99_edge = cdf_quantile(hist, 0.99)
    rm = round_ms / 1000.0

    undetected = _arr(curves, "swim_undetected_deaths")
    events = detection_latencies(undetected, kill_rounds=kill_rounds)
    det = [e["detected_rounds"] for e in events
           if e["detected_rounds"] is not None]

    backlog = _arr(curves, "queue_backlog")
    stale_max = _arr(curves, "staleness_max")
    return ConvergenceReport(
        engine=engine,
        rounds=rounds,
        round_ms=round_ms,
        converged_round=converged_round,
        ttc_s=(
            None if converged_round is None else converged_round * rm
        ),
        need_last=float(need[-1]) if rounds else 0.0,
        mismatches_last=float(mism[-1]) if rounds else 0.0,
        staleness_last=float(stale[-1]) if rounds else 0.0,
        staleness_p50=(
            float(np.percentile(stale, 50)) if rounds else float("nan")
        ),
        staleness_p99=(
            float(np.percentile(stale, 99)) if rounds else float("nan")
        ),
        staleness_max_peak=float(stale_max.max()) if rounds else 0.0,
        vis_total=total,
        vis_hist=hist.astype(np.int64).tolist(),
        vis_cdf=cdf,
        vis_p50_bucket=p50_b,
        vis_p99_bucket=p99_b,
        vis_p50_s=p50_edge * rm,
        vis_p99_s=p99_edge * rm,
        false_alarms_total=float(_arr(curves, "swim_false_alarms").sum()),
        flaps_total=float(_arr(curves, "swim_flaps").sum()),
        detection_events=events,
        detection_max_rounds=max(det) if det else None,
        undetected_unresolved=sum(
            1 for e in events if e["detected_rounds"] is None
        ),
        queue_backlog_peak=float(backlog.max()) if rounds else 0.0,
        queue_backlog_last=float(backlog[-1]) if rounds else 0.0,
        msgs_total=float(_arr(curves, "msgs").sum()),
        applied_total=float(
            _arr(curves, "applied_broadcast").sum()
            + _arr(curves, "applied_sync").sum()
        ),
        sessions_total=float(_arr(curves, "sessions").sum()),
    )


def report_from_flight(
    path: str, round_ms: float = 500.0, kill_rounds=None
) -> ConvergenceReport:
    """ConvergenceReport from a flight-recorder JSONL alone — the crashed
    or still-running run's record is enough; no final state needed."""
    curves, _chunks = replay_flight(path)
    engine = flight_header(path).get("engine", "unknown")
    return report_from_curves(
        curves, engine=engine, round_ms=round_ms, kill_rounds=kill_rounds
    )


def load_report(path: str, round_ms: float = 500.0) -> ConvergenceReport:
    """Load a report from either a flight JSONL or a saved report JSON
    (``obs report --json`` output) — the `obs diff` input format."""
    # Classify by parsing the FIRST LINE as JSON and looking at its keys:
    # a flight JSONL's first record is {"kind": "flight"|...}, a saved
    # report is one JSON object whose "schema" names the report format.
    # (A fixed-size substring sniff misclassifies large reports whose
    # trailing schema key falls outside the sniffed prefix.)
    with open(path) as f:
        first = f.readline().strip()
    obj = None
    try:
        obj = json.loads(first)
    except ValueError:
        # Not one-object-per-line: a pretty-printed report parses as a
        # whole file; anything else falls through to the flight reader.
        try:
            with open(path) as f:
                obj = json.load(f)
        except ValueError:
            pass
    if isinstance(obj, dict) and "kind" not in obj:
        if obj.get("schema") != REPORT_SCHEMA:
            raise ValueError(
                f"{path}: not a flight JSONL or {REPORT_SCHEMA} report"
            )
        obj.pop("schema", None)
        # Undo the JSON-safe encoding (to_dict): null -> NaN on float
        # fields, "inf" -> inf.
        nan_fields = {
            "staleness_p50", "staleness_p99", "vis_p50_s", "vis_p99_s",
        }
        for k, v in obj.items():
            if v == "inf":
                obj[k] = float("inf")
            elif v is None and k in nan_fields:
                obj[k] = float("nan")
        return ConvergenceReport(**obj)
    return report_from_flight(path, round_ms=round_ms)


def publish_report(registry, report: ConvergenceReport,
                   engine: str | None = None) -> None:
    """Fold run-level verdicts into a MetricsRegistry as
    ``corro_kernel_health_*`` gauges (the per-round curve series are
    published by ``telemetry.publish_curves``).

    Latency sentinels: -1 = no data (no visibility events), -2 = the
    percentile landed in the overflow bucket (worse than every finite
    edge — the regression case); ``vis_overflow_events`` carries the raw
    overflow-bucket count so dashboards can alert on it directly.
    """
    eng = engine or report.engine

    def lat_sentinel(x: float) -> float:
        if x is None or math.isnan(x):
            return -1.0
        if math.isinf(x):
            return -2.0
        return x

    overflow_events = float(report.vis_hist[-1]) if report.vis_hist else 0.0
    g = [
        ("converged", 1.0 if report.converged else 0.0,
         "run reached all-quiet convergence"),
        ("converged_round",
         float(report.converged_round)
         if report.converged_round is not None else -1.0,
         "first all-quiet round (-1 = never)"),
        ("staleness_p99", _nan_to(report.staleness_p99, -1.0),
         "p99 of per-round cluster staleness mass"),
        ("staleness_peak", report.staleness_max_peak,
         "worst single-node watermark lag seen"),
        ("vis_p50_seconds", lat_sentinel(report.vis_p50_s),
         "delivery latency p50 (bucket upper edge, simulated s; "
         "-1 = no data, -2 = overflow bucket)"),
        ("vis_p99_seconds", lat_sentinel(report.vis_p99_s),
         "delivery latency p99 (bucket upper edge, simulated s; "
         "-1 = no data, -2 = overflow bucket)"),
        ("vis_overflow_events", overflow_events,
         "visibility events past the last finite latency edge"),
        ("detection_max_rounds",
         float(report.detection_max_rounds)
         if report.detection_max_rounds is not None else -1.0,
         "slowest churn-event rounds-to-detection"),
        ("queue_backlog_peak", report.queue_backlog_peak,
         "peak pending-broadcast backlog"),
    ]
    for name, value, help_ in g:
        registry.gauge(
            f"corro_kernel_health_{name}", f"health plane: {help_}"
        ).set(float(value), engine=eng)


def _nan_to(x: float, repl: float) -> float:
    return repl if (x is None or math.isnan(x) or math.isinf(x)) else x


def _json_num(x):
    """JSON-safe scalar: NaN -> null, +/-inf -> "inf" (strict parsers
    reject the Python json module's bare NaN/Infinity tokens)."""
    if isinstance(x, float):
        if math.isnan(x):
            return None
        if math.isinf(x):
            return "inf"
    return x


# Metrics compared by `obs diff`: (field, larger-is-worse, absolute slack
# added to the tolerance band — keeps zero/zero and bucket-edge jitter
# from flagging).
DIFF_METRICS = (
    ("converged_round", True, 2.0),
    ("vis_p50_s", True, 0.0),
    ("vis_p99_s", True, 0.0),
    ("staleness_p99", True, 1.0),
    ("staleness_max_peak", True, 1.0),
    ("detection_max_rounds", True, 2.0),
    ("queue_backlog_peak", True, 8.0),
    ("undetected_unresolved", True, 0.0),
)


def diff_reports(
    baseline: ConvergenceReport,
    candidate: ConvergenceReport,
    tolerance: float = 0.2,
) -> dict:
    """BENCH-style regression diff: flag candidate metrics worse than
    baseline by more than ``tolerance`` (relative) plus a per-metric
    absolute slack. Non-convergence where the baseline converged is
    always a regression. Returns {"regressions": [...], "rows": [...]}.
    """
    rows = []
    regressions = []
    if baseline.converged and not candidate.converged:
        regressions.append(
            "candidate did not converge (baseline did: round "
            f"{baseline.converged_round})"
        )
    for name, larger_worse, slack in DIFF_METRICS:
        a = getattr(baseline, name)
        b = getattr(candidate, name)
        # inf is a real (worst-bucket) value and must participate in the
        # comparison — a candidate regressing into the overflow bucket is
        # exactly what the gate exists to catch; only unknowns skip.
        af = float(a) if a is not None else math.nan
        bf = float(b) if b is not None else math.nan
        row = {
            "metric": name, "baseline": _json_num(a),
            "candidate": _json_num(b), "ok": True,
        }
        if not (math.isnan(af) or math.isnan(bf)):
            if larger_worse:
                worse = bf > af * (1.0 + tolerance) + slack
            else:
                worse = bf < af * (1.0 - tolerance) - slack
            if worse:
                row["ok"] = False
                regressions.append(
                    f"{name}: {b} vs baseline {a} "
                    f"(tolerance {tolerance:.0%} + {slack:g})"
                )
        rows.append(row)
    return {"regressions": regressions, "rows": rows}


GEO_REGIONS = 4  # region count of the geo scenario family (<= PROP_REGIONS)

# The committed adaptive-dissemination tuning for the geo scenario family
# (docs/PERFORMANCE.md "Adaptive dissemination"): the three mechanisms
# composed, measured on the 96x48 geo smoke against the push-only
# baseline (EPIDEMIC_BASELINE.json vs EPIDEMIC_BASELINE_ADAPTIVE.json;
# the `dissemination` entry of bench_budget.json gates the comparison in
# CI). One dict so `obs record --adaptive`, the smoke, and the tests all
# run the exact same knobs.
ADAPTIVE_GOSSIP = {
    "rumor_kill_k": 2,
    "pull_switch_age": 2,
    "age_forward": True,
}


def churned_demo_cluster(
    nodes: int = 128,
    rounds: int = 64,
    samples: int = 64,
    churn: bool = True,
    seed: int = 0,
    geo: bool = False,
    adaptive: bool = False,
):
    """Small dense cluster with a mid-run kill/revive wave of NON-writer
    nodes (writers stay up so sampled-write bookkeeping remains exact) —
    the one scenario builder shared by `obs record`, the CI convergence
    artifact, and the health-plane tests.

    ``adaptive=True`` (geo only) additionally enables the adaptive
    dissemination plane at the committed ``ADAPTIVE_GOSSIP`` tuning —
    the same scenario, schedule, and RNG streams, so the push-only and
    adaptive flights are directly comparable copy for copy.

    ``geo=True`` is the WAN variant of the same scenario family: the
    cluster splits into ``GEO_REGIONS`` contiguous regions on the
    synthetic circle geography (``region_rtt="geo"`` — ring classes
    span the full 0-5 RTT bucket range instead of flat ring-1), writers
    spread evenly across regions, and the propagation-topology plane is
    enabled (``prop_observe``) — the committed ``EPIDEMIC_BASELINE``
    scenario. The default (flat) variant's RNG stream and schedule are
    byte-identical to before the geo axis existed, so the committed
    ``CONVERGENCE_BASELINE`` stays comparable.

    Returns (cfg, topo, sched, kill_rounds). Kills ``nodes // 16``
    victims at ``rounds // 4``, revives them by ``rounds // 2``, and
    drains the last third so the run can converge.
    """
    import numpy as np  # noqa: F811 (explicit: jax imports are lazy here)

    from corrosion_tpu.models.baselines import _cfg
    from corrosion_tpu.sim.engine import Schedule

    n_writers = max(4, min(16, nodes // 8))
    if adaptive and not geo:
        raise ValueError(
            "adaptive=True is defined for the geo scenario family only "
            "(the flat variant's RNG stream is pinned pre-adaptive)"
        )
    adaptive_kw = dict(ADAPTIVE_GOSSIP) if adaptive else {}
    if geo:
        sizes = [nodes // GEO_REGIONS] * GEO_REGIONS
        sizes[-1] += nodes - sum(sizes)
        # Writers spread evenly around the circle so the epidemic has to
        # cross every ring, deduped in case nodes is tiny.
        writers = sorted({
            min(round(i * nodes / n_writers), nodes - 1)
            for i in range(n_writers)
        })
        n_writers = len(writers)
        cfg, topo = _cfg(
            nodes, writers=writers, regions=sizes, region_rtt="geo",
            sync_interval=5, n_cells=0, prop_observe=True,
            **adaptive_kw,
        )
        writer_set = set(writers)
        non_writers = np.asarray(
            [i for i in range(nodes) if i not in writer_set]
        )
    else:
        cfg, topo = _cfg(
            nodes, writers=list(range(n_writers)), sync_interval=5,
            n_cells=0,
        )
        non_writers = np.arange(n_writers, nodes)
    rng = np.random.default_rng(seed)
    writes = (rng.random((rounds, n_writers)) < 0.15).astype(np.uint32)
    drain = max(rounds // 3, 1)
    writes[rounds - drain:, :] = 0
    kill = revive = None
    kill_rounds: list[int] = []
    if churn and rounds >= 16:
        kill = np.zeros((rounds, nodes), bool)
        revive = np.zeros((rounds, nodes), bool)
        victims = rng.choice(
            non_writers, size=max(nodes // 16, 1),
            replace=False,
        )
        k_at = rounds // 4
        r_at = min(rounds // 2, rounds - drain)
        kill[k_at, victims] = True
        revive[r_at, victims] = True
        kill_rounds = [k_at]
    sched = Schedule(
        writes=writes, kill=kill, revive=revive
    ).make_samples(samples)
    return cfg, topo, sched, kill_rounds


def record_demo_flight(
    out: str,
    nodes: int = 128,
    rounds: int = 64,
    churn: bool = False,
    seed: int = 0,
    progress=None,
    geo: bool = False,
    adaptive: bool = False,
) -> dict:
    """Run a small dense cluster (optionally with churn) recording a
    flight JSONL — the `obs record` backend and the CI convergence
    artifact. Returns run facts (kill rounds, convergence booleans).
    ``geo=True`` records the WAN-ring variant with the propagation
    plane enabled — the `obs epidemic` / ``EPIDEMIC_BASELINE`` source.

    Deliberately modest: a CPU-friendly cluster whose flight record
    exercises every health key, not a benchmark.
    """
    import numpy as np  # noqa: F811

    from corrosion_tpu.sim.engine import simulate
    from corrosion_tpu.sim.telemetry import FlightRecorder, KernelTelemetry

    cfg, topo, sched, kill_rounds = churned_demo_cluster(
        nodes=nodes, rounds=rounds, churn=churn, seed=seed, geo=geo,
        adaptive=adaptive,
    )
    tele = KernelTelemetry(
        engine="dense", progress=progress,
        recorder=FlightRecorder(out, engine="dense", mode="w"),
    )
    final, curves = simulate(
        cfg, topo, sched, seed=seed,
        max_chunk=max(rounds // 4, 1), telemetry=tele,
    )
    tele.recorder.close()
    # Time-to-convergence: the first round after which outstanding need
    # stays zero for the rest of the run (None = never converged) — the
    # adaptive-vs-push equal-TTC gate's measured quantity.
    need = np.asarray(curves["need"], dtype=np.float64)
    nz = np.nonzero(need > 0)[0]
    if need.size and float(need[-1]) == 0.0:
        converged_round = int(nz[-1]) + 1 if nz.size else 0
    else:
        converged_round = None
    return {
        "flight": os.path.abspath(out),
        "nodes": nodes,
        "rounds": rounds,
        "geo": geo,
        "adaptive": adaptive,
        "regions": GEO_REGIONS if geo else 1,
        "fanout": cfg.gossip.fanout,
        "kill_rounds": kill_rounds,
        "need_last": float(need[-1]) if need.size else None,
        "converged_round": converged_round,
        "staleness_last": float(np.asarray(curves["staleness_sum"])[-1]),
        "mismatches_last": float(np.asarray(curves["mismatches"])[-1]),
    }
