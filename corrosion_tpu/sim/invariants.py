"""Post-heal invariant checking + the seeded chaos fuzzer.

The chaos plane's judge: run an engine under a :class:`~corrosion_tpu.sim.
faults.FaultPlan` on a small standard scenario, then — after the last
fault clears — verify the protocol actually RECOVERED correctly, not
just that the run finished:

- **recovery**: the record goes quiet after the heal round
  (``sim.health.recovery_after_heal``: need, staleness, and SWIM
  undetected-deaths all zero to the end) and the recovery time is
  reported through sim/health.py.
- **durability**: no write acknowledged by a surviving writer is lost —
  every live node's watermark reaches every writer's committed head.
- **agreement**: live nodes' CRDT cell state equals the serial-merge
  ground truth (``serial_merge_reference`` /
  ``serial_merge_reference_sparse``) — convergence over CONTENT, not
  just watermarks.
- **membership**: zero ``undetected_deaths`` at the end, ground-truth
  liveness matches the plan (killed-forever stay dead), and no
  resurrection of wiped identities (a wiped+revived node rejoins at a
  strictly higher incarnation). ``mismatches`` about LIVE nodes is
  deliberately NOT asserted: down beliefs are sticky until down-GC
  (the reference's ``remove_down_after`` is 48 h), so a probe-loss
  storm legitimately leaves them nonzero.

Engine quirks the suite accounts for (gossip.revive_sync's semantics
note): the sparse engine degrades crash-with-state-wipe to pause-resume
(bounded deviation tables), and the chunk plane drops partition/flap
and probe-loss components (no region topology, no SWIM). Degradations
are recorded in the report's ``facts``.

The fuzzer (:func:`fuzz`) samples random healing plans, runs the suite,
and on failure shrinks the plan — greedy component drops, then
round-window bisection (sim/faults.shrink_plan) — to a minimal JSON
repro artifact. Scenario shapes are FIXED (48 nodes, 4 regions) and
every fault axis is always threaded (zeros when a plan lacks it), so a
whole fuzz batch shares one compile per engine.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

import numpy as np

from corrosion_tpu.sim import faults as faults_mod
from corrosion_tpu.sim import health as health_mod
from corrosion_tpu.sim.faults import CompiledFaults, Fault, FaultPlan

REPRO_SCHEMA = "corro-chaos-repro/1"

# One standard cluster shape for every engine scenario: plans are
# portable across engines and a fuzz batch reuses each engine's compile.
STD_NODES = 48
STD_REGIONS = 4
# Writer / stream-origin nodes — churn must not take out the
# acknowledgers the durability invariant is stated for (and the chunk
# plane's origins are each stream's only guaranteed full holder).
DENSE_WRITERS = (0, 12, 24, 36, 1, 13)
MIXED_WRITERS = (0, 12, 24, 36)
CHUNK_ORIGINS = (2, 14, 26)
PROTECTED = tuple(sorted(set(DENSE_WRITERS + MIXED_WRITERS + CHUNK_ORIGINS)))

ENGINES = ("dense", "sparse", "chunk", "mixed")


@dataclass
class InvariantReport:
    engine: str
    ok: bool
    violations: list = field(default_factory=list)
    heal_round: int = 0
    recovery: dict = field(default_factory=dict)
    facts: dict = field(default_factory=dict)
    plan: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "engine": self.engine, "ok": self.ok,
            "violations": list(self.violations),
            "heal_round": self.heal_round, "recovery": self.recovery,
            "facts": self.facts, "plan": self.plan,
        }

    def render(self) -> str:
        head = f"[{self.engine}] {'OK' if self.ok else 'FAIL'}"
        rec = self.recovery.get("recovery_rounds")
        head += (
            f" heal@{self.heal_round}"
            + (f" recovered +{rec} rounds" if rec is not None
               else " NOT RECOVERED")
        )
        lines = [head]
        lines += [f"  violation: {v}" for v in self.violations]
        if self.facts.get("degraded"):
            lines.append(f"  degraded: {', '.join(self.facts['degraded'])}")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# Plan portability: per-engine degradation + dense fault-axis threading.


def plan_for_engine(plan: FaultPlan, engine: str) -> tuple[FaultPlan, list]:
    """Degrade a plan to what ``engine`` can express. Returns
    (plan, notes); notes name every dropped/weakened component."""
    notes: list = []
    out = []
    for f in plan.faults:
        if engine == "chunk" and f.kind in ("partition", "flap"):
            notes.append(f"{f.kind} dropped (chunk plane has no regions)")
            continue
        if engine == "chunk" and f.kind == "probe_loss":
            notes.append("probe_loss dropped (chunk plane has no SWIM)")
            continue
        if engine == "sparse" and f.kind == "churn" and f.wipe:
            notes.append(
                "wipe degraded to pause-resume (sparse engine's bounded "
                "deviation tables)"
            )
            f = Fault(
                "churn", f.start, f.stop, nodes=f.nodes,
                revive_at=f.revive_at, wipe=False,
            )
        out.append(f)
    return FaultPlan(plan.rounds, tuple(out), plan.name), notes


def _densify(c: CompiledFaults, n_nodes: int, n_regions: int,
             wipe: bool = True) -> CompiledFaults:
    """Thread EVERY fault axis (zeros where the plan is silent) so all
    plans of one batch share one engine trace. Zero masks are
    behavior-identical to absent ones within that trace."""
    r = c.rounds
    if c.loss is None:
        c.loss = np.zeros((r, n_regions), np.float32)
    if c.probe_loss is None:
        c.probe_loss = np.zeros(r, np.float32)
    if c.kill is None:
        c.kill = np.zeros((r, n_nodes), bool)
        c.revive = np.zeros((r, n_nodes), bool)
    if c.revive is None:
        c.revive = np.zeros((r, n_nodes), bool)
    if wipe and c.wipe is None:
        c.wipe = np.zeros((r, n_nodes), bool)
    return c


# ---------------------------------------------------------------------------
# Standard scenarios. Shapes depend only on ``rounds`` so a fuzz batch
# (fixed rounds) compiles each engine once.


def _write_window(plan: FaultPlan) -> int:
    """Writes stop at the later of the heal round and ~55% of the run,
    leaving a drain tail that can prove recovery."""
    drain = max(plan.rounds // 3, 8)
    return max(min(plan.heal_round + 2, plan.rounds - drain), 4)


def _dense_scenario(plan: FaultPlan, seed: int):
    from corrosion_tpu.models.baselines import _cfg
    from corrosion_tpu.sim.engine import Schedule

    cfg, topo = _cfg(
        STD_NODES, writers=list(DENSE_WRITERS),
        regions=[STD_NODES // STD_REGIONS] * STD_REGIONS,
        sync_interval=5, sync_budget=512, sync_chunk=128,
        n_cells=64,
        # down-GC keeps sticky down beliefs from pinning memory forever
        # (remove_down_after); membership convergence is still not an
        # asserted invariant (module docstring).
        swim_kw={"down_gc_rounds": 24},
    )
    rng = np.random.default_rng(seed)
    writes = np.zeros((plan.rounds, len(DENSE_WRITERS)), np.uint32)
    w_stop = _write_window(plan)
    writes[:w_stop] = (
        rng.random((w_stop, len(DENSE_WRITERS))) < 0.25
    ).astype(np.uint32)
    writes[0, :] = 1  # every stream exists before any fault can start
    sched = Schedule(writes=writes).make_samples(32)
    return cfg, topo, sched


def run_dense(plan: FaultPlan, seed: int = 0) -> InvariantReport:
    from corrosion_tpu.ops import gossip
    from corrosion_tpu.sim.engine import simulate, visibility_latencies

    cfg, topo, sched = _dense_scenario(plan, seed)
    compiled = _densify(
        plan.compile(STD_NODES, STD_REGIONS), STD_NODES, STD_REGIONS
    )
    sched = faults_mod.apply_plan(sched, compiled, STD_NODES, STD_REGIONS)
    final, curves = simulate(cfg, topo, sched, seed=seed)

    rep = _base_report("dense", plan, compiled, curves, cfg.round_ms)
    alive = np.asarray(final.swim.alive)
    _check_liveness(rep, plan, alive)
    _check_durability(
        rep, alive, np.asarray(final.data.head),
        np.asarray(final.data.contig),
    )
    if cfg.gossip.n_cells > 0:
        ref = gossip.serial_merge_reference(final.data.head, cfg.gossip)
        pc = gossip.node_cells(final.data, cfg.gossip)
        _check_cell_agreement(
            rep, pc.cl, pc.col_version, pc.value_rank, ref, alive,
            "serial merge",
        )
    _check_no_resurrection(rep, plan, final.swim)
    if rep.recovery.get("recovered_round") is not None:
        lat = visibility_latencies(final, sched, cfg, alive_only=True)
        if lat["unseen"] > 0:
            rep.violations.append(
                f"{lat['unseen']} sampled (write, live node) pairs never "
                f"became visible despite recovery"
            )
        rep.facts["vis_p99_s"] = lat["p99_s"]
    rep.ok = not rep.violations
    return rep


def _sparse_scenario(plan: FaultPlan, seed: int):
    from corrosion_tpu.models.baselines import anywrite_sparse

    cfg, topo, sched = anywrite_sparse(
        n=STD_NODES, w_hot=16, rounds=plan.rounds,
        n_regions=STD_REGIONS, epoch_rounds=8, cohort=5, burst_writes=1,
        samples=0, seed=seed, k_dev=16, demote_after=1,
    )
    return cfg, topo, sched


def run_sparse(plan: FaultPlan, seed: int = 0) -> InvariantReport:
    from corrosion_tpu.ops.sparse_writers import (
        serial_merge_reference_sparse,
    )
    from corrosion_tpu.sim.sparse_engine import (
        final_head_full,
        simulate_sparse,
    )

    plan_e, notes = plan_for_engine(plan, "sparse")
    cfg, topo, sched = _sparse_scenario(plan_e, seed)
    compiled = _densify(
        plan_e.compile(STD_NODES, STD_REGIONS, allow_wipe=False),
        STD_NODES, STD_REGIONS, wipe=False,
    )
    sched = faults_mod.apply_plan(sched, compiled, STD_NODES, STD_REGIONS)
    sstate, swim_state, _vis, curves, info = simulate_sparse(
        cfg, topo, sched, seed=seed
    )

    rep = _base_report("sparse", plan_e, compiled, curves, cfg.round_ms)
    rep.facts["degraded"] = notes
    alive = np.asarray(swim_state.alive)
    _check_liveness(rep, plan_e, alive)

    # Durability on the rotating-slot plane: hot slots at head for live
    # nodes, no outstanding deviation entries anywhere.
    slot_writer = np.asarray(sstate.slot_writer)
    occ = slot_writer >= 0
    contig = np.asarray(sstate.data.contig)[:, occ]
    head = np.asarray(sstate.data.head)[occ]
    lag = (contig < head[None, :]) & alive[:, None]
    if lag.any():
        n_bad = int(lag.any(axis=1).sum())
        rep.violations.append(
            f"acknowledged writes lost on the hot plane: {n_bad} live "
            f"node(s) below a writer's committed head"
        )
    if bool(np.asarray(sstate.dev_any)):
        rep.violations.append(
            "cold-plane deviation entries outstanding at record end"
        )
    if cfg.gossip.n_cells > 0:
        hf = final_head_full(sstate)
        ref = serial_merge_reference_sparse(hf, cfg.gossip)
        n, k = cfg.n_nodes, cfg.gossip.n_cells
        _check_cell_agreement(
            rep,
            np.asarray(sstate.data.cells.cl).reshape(n, k),
            np.asarray(sstate.data.cells.col_version).reshape(n, k),
            np.asarray(sstate.data.cells.value_rank).reshape(n, k),
            ref, alive, "sparse serial merge",
        )
    _check_no_resurrection(rep, plan_e, swim_state)
    rep.facts["epochs"] = info["epochs"]
    rep.ok = not rep.violations
    return rep


def run_chunks(plan: FaultPlan, seed: int = 0) -> InvariantReport:
    import jax.numpy as jnp

    from corrosion_tpu.ops import chunks as chunk_ops
    from corrosion_tpu.ops.chunks import ChunkConfig
    from corrosion_tpu.sim.chunk_engine import simulate_chunks

    plan_e, notes = plan_for_engine(plan, "chunk")
    ccfg = ChunkConfig(
        n_nodes=STD_NODES, n_streams=len(CHUNK_ORIGINS), cap=16,
        chunk_len=128, fanout=3, k_in=6, sync_interval=4,
        gap_requests=4, sync_seq_budget=2048,
    )
    last_seq = np.full(len(CHUNK_ORIGINS), 1023, np.int32)
    # Compiled at the standard region count: the chunk engine reads the
    # worst-region ``loss_scalar`` view, so region-targeted loss bursts
    # still apply (cluster-wide).
    compiled = _densify(
        plan_e.compile(STD_NODES, STD_REGIONS), STD_NODES, STD_REGIONS
    )
    compiled.partition = None  # plan_for_engine dropped the components
    state, metrics = simulate_chunks(
        ccfg, np.asarray(CHUNK_ORIGINS, np.int32), last_seq,
        rounds=plan_e.rounds, seed=seed, faults=compiled,
    )
    curves = metrics["curves"]

    rep = _base_report("chunk", plan_e, compiled, curves, 500.0)
    rep.facts["degraded"] = notes
    alive = compiled.alive_curve(STD_NODES)[-1]
    applied = np.asarray(
        chunk_ops.applied_mask(state, jnp.asarray(last_seq), ccfg)
    )
    missing = (~applied) & alive[:, None]
    if missing.any():
        rep.violations.append(
            f"{int(missing.sum())} live (node, stream) pairs never "
            f"reassembled their stream"
        )
    rep.facts["applied_frac"] = metrics["applied_frac"]
    rep.ok = not rep.violations
    return rep


def _mixed_scenario(plan: FaultPlan, seed: int):
    """Small mixed workload: MIXED_WRITERS background writers, two of
    them each committing one large multi-chunk transaction before the
    fault window closes (the mixed_storm recipe at suite scale)."""
    from corrosion_tpu.models.baselines import _cfg
    from corrosion_tpu.ops.chunks import ChunkConfig
    from corrosion_tpu.sim.engine import Schedule
    from corrosion_tpu.sim.mixed_engine import StreamSpec

    rounds = plan.rounds
    streams = 2
    cfg, topo = _cfg(
        STD_NODES, writers=list(MIXED_WRITERS),
        regions=[STD_NODES // STD_REGIONS] * STD_REGIONS,
        sync_interval=5, sync_budget=512, sync_chunk=128,
        n_cells=64, swim_kw={"down_gc_rounds": 24},
    )
    rng = np.random.default_rng(seed)
    w_stop = _write_window(plan)
    writes = np.zeros((rounds, len(MIXED_WRITERS)), np.uint32)
    writes[:w_stop] = (
        rng.random((w_stop, len(MIXED_WRITERS))) < 0.2
    ).astype(np.uint32)
    writes[0, :] = 1
    commit_round = np.asarray(
        sorted(rng.integers(2, max(w_stop - 2, 3), streams)), np.int32
    )
    version = np.zeros(streams, np.uint32)
    for s in range(streams):
        version[s] = writes[: commit_round[s], s].sum() + 1
    spec = StreamSpec(
        writer=np.arange(streams, dtype=np.int32),
        version=version,
        commit_round=commit_round,
        last_seq=np.full(streams, 511, np.int32),
    )
    ccfg = ChunkConfig(
        n_nodes=STD_NODES, n_streams=streams, cap=16, chunk_len=128,
        fanout=3, k_in=6, sync_interval=4, gap_requests=4,
        sync_seq_budget=2048,
    )
    sched = Schedule(writes=writes).make_samples(16)
    # Samples at/after a big version shift up one slot (mixed_storm's
    # bookkeeping rule).
    for i in range(len(sched.sample_writer)):
        w = sched.sample_writer[i]
        if w < streams and sched.sample_ver[i] >= version[w]:
            sched.sample_ver[i] += 1
    return cfg, ccfg, topo, sched, spec


def run_mixed(plan: FaultPlan, seed: int = 0) -> InvariantReport:
    from corrosion_tpu.ops import gossip
    from corrosion_tpu.sim.mixed_engine import simulate_mixed

    cfg, ccfg, topo, sched, spec = _mixed_scenario(plan, seed)
    compiled = _densify(
        plan.compile(STD_NODES, STD_REGIONS), STD_NODES, STD_REGIONS
    )
    sched = faults_mod.apply_plan(sched, compiled, STD_NODES, STD_REGIONS)
    final, curves = simulate_mixed(
        cfg, ccfg, topo, sched, spec, seed=seed
    )

    rep = _base_report("mixed", plan, compiled, curves, cfg.round_ms)
    alive = np.asarray(final.swim.alive)
    _check_liveness(rep, plan, alive)
    heads = np.asarray(final.data.head)
    _check_durability(rep, alive, heads, np.asarray(final.data.contig))
    # The big versions really occupy their slots and reassembled at
    # every live node (directly or via sync backfill).
    for s in range(len(spec.writer)):
        if heads[spec.writer[s]] < spec.version[s]:
            rep.violations.append(
                f"big version {int(spec.version[s])} of writer "
                f"{int(spec.writer[s])} never committed"
            )
    not_applied = (~np.asarray(final.applied_before)) & alive[:, None]
    if not_applied.any():
        rep.violations.append(
            f"{int(not_applied.sum())} live (node, stream) pairs never "
            f"applied their big version"
        )
    if cfg.gossip.n_cells > 0:
        ref = gossip.serial_merge_reference(final.data.head, cfg.gossip)
        pc = gossip.node_cells(final.data, cfg.gossip)
        _check_cell_agreement(
            rep, pc.cl, pc.col_version, pc.value_rank, ref, alive,
            "serial merge (big versions included)",
        )
    _check_no_resurrection(rep, plan, final.swim)
    rep.ok = not rep.violations
    return rep


# ---------------------------------------------------------------------------
# Shared checks.


def _base_report(engine, plan, compiled, curves, round_ms):
    rep = InvariantReport(
        engine=engine, ok=False, heal_round=plan.heal_round,
        plan=plan.to_dict(),
    )
    rep.recovery = health_mod.recovery_after_heal(
        curves, plan.heal_round, round_ms=round_ms
    )
    if not plan.heals:
        rep.violations.append(
            "plan never heals (a fault component has no clear round) — "
            "post-heal invariants are unsatisfiable"
        )
    if rep.recovery["recovered_round"] is None:
        need = np.asarray(curves["need"], dtype=np.float64)
        stale = np.asarray(curves["staleness_sum"], dtype=np.float64)
        undet = np.asarray(
            curves["swim_undetected_deaths"], dtype=np.float64
        )
        rep.violations.append(
            f"did not recover after heal@{plan.heal_round}: record ends "
            f"with need={need[-1]:g} staleness={stale[-1]:g} "
            f"undetected_deaths={undet[-1]:g}"
        )
    rep.facts["msgs_total"] = float(
        np.asarray(curves["msgs"], dtype=np.float64).sum()
    )
    rep.facts["chaos_lost_msgs"] = float(
        np.asarray(curves["chaos_lost_msgs"], dtype=np.float64).sum()
    )
    rep.facts["chaos_wiped"] = float(
        np.asarray(curves["chaos_wiped"], dtype=np.float64).sum()
    )
    return rep


def _check_liveness(rep, plan, alive):
    dead_forever = set(plan.killed_forever())
    expect = np.asarray(
        [i not in dead_forever for i in range(len(alive))], bool
    )
    if not (alive == expect).all():
        drift = np.nonzero(alive != expect)[0][:8]
        rep.violations.append(
            f"ground-truth liveness drifted from the plan at nodes "
            f"{drift.tolist()}"
        )


def _check_cell_agreement(rep, cl, cv, vr, ref, alive, label):
    """Live nodes' CRDT registers must equal the serial-merge ground
    truth ``ref`` (one shared comparison for all three engines that
    carry a cell plane)."""
    bad = ~(
        (np.asarray(cl) == np.asarray(ref.cl)[None, :])
        & (np.asarray(cv) == np.asarray(ref.col_version)[None, :])
        & (np.asarray(vr) == np.asarray(ref.value_rank)[None, :])
    ).all(axis=1)
    bad &= alive
    if bad.any():
        rep.violations.append(
            f"CRDT cell disagreement vs {label} on {int(bad.sum())} live "
            f"node(s), first node {int(np.nonzero(bad)[0][0])}"
        )


def _check_durability(rep, alive, head, contig):
    lag = (contig < head[None, :]) & alive[:, None]
    if lag.any():
        i, w = np.nonzero(lag)
        rep.violations.append(
            f"acknowledged writes lost: {int(lag.any(axis=1).sum())} live "
            f"node(s) below a committed head (first: node {int(i[0])} "
            f"holds {int(contig[i[0], w[0]])}/{int(head[w[0]])} of writer "
            f"{int(w[0])})"
        )


def _check_no_resurrection(rep, plan, swim_state):
    """A wiped+revived node must rejoin as a NEW identity (incarnation
    strictly above the wiped one's floor of 0) — stale pre-wipe beliefs
    must never outrank it back to life."""
    wiped = [
        n for n in plan.wipes() if n not in set(plan.killed_forever())
    ]
    if not wiped:
        return
    inc = np.asarray(swim_state.incarnation)[wiped]
    if (inc < 1).any():
        rep.violations.append(
            f"wiped node(s) {np.asarray(wiped)[inc < 1].tolist()} rejoined "
            f"without an incarnation bump — resurrection of the wiped "
            f"identity"
        )


RUNNERS = {
    "dense": run_dense,
    "sparse": run_sparse,
    "chunk": run_chunks,
    "mixed": run_mixed,
}


def run_suite(
    plan: FaultPlan, engines=ENGINES, seed: int = 0, progress=None
) -> list:
    reports = []
    for eng in engines:
        if progress is not None:
            progress.write(f"[chaos] {eng}: {plan.describe()}\n")
            progress.flush()
        reports.append(RUNNERS[eng](plan, seed=seed))
    return reports


# ---------------------------------------------------------------------------
# The fuzzer.


def fuzz(
    seed: int = 0,
    plans: int = 4,
    engines=ENGINES,
    rounds: int = 64,
    out_dir: str | None = None,
    break_heal: bool = False,
    shrink_evals: int = 24,
    allow_wipe: bool = True,
    progress=None,
) -> dict:
    """Seeded chaos fuzz: ``plans`` random fault plans through the
    invariant suite on ``engines``. On a failure, shrink the plan
    against the first failing engine and (with ``out_dir``) write a
    minimal JSON repro artifact. Returns a summary dict with
    ``failures`` (count) and ``repros`` (artifact paths/dicts)."""
    rng = np.random.default_rng(seed)
    results = []
    repros = []
    for i in range(plans):
        plan = faults_mod.random_plan(
            rng, rounds, STD_REGIONS, STD_NODES, protect=PROTECTED,
            allow_wipe=allow_wipe, break_heal=break_heal,
        )
        plan = FaultPlan(plan.rounds, plan.faults, name=f"fuzz-{seed}-{i}")
        reports = run_suite(plan, engines, seed=seed, progress=progress)
        failed = [r for r in reports if not r.ok]
        entry = {
            "plan": plan.to_dict(),
            "describe": plan.describe(),
            "reports": [r.to_dict() for r in reports],
            "ok": not failed,
        }
        if failed:
            eng = failed[0].engine
            runner = RUNNERS[eng]

            def still_fails(p, runner=runner):
                return not runner(p, seed=seed).ok

            minimal, evals = faults_mod.shrink_plan(
                plan, still_fails, max_evals=shrink_evals
            )
            final_rep = runner(minimal, seed=seed)
            repro = {
                "schema": REPRO_SCHEMA,
                "seed": seed,
                "engine": eng,
                "scenario": {
                    "nodes": STD_NODES, "regions": STD_REGIONS,
                    "protected": list(PROTECTED),
                },
                "original_plan": plan.to_dict(),
                "plan": minimal.to_dict(),
                "shrink_evals": evals,
                "violations": list(final_rep.violations),
            }
            entry["repro"] = repro
            if out_dir is not None:
                os.makedirs(out_dir, exist_ok=True)
                path = os.path.join(
                    out_dir, f"chaos_repro_{seed}_{i}_{eng}.json"
                )
                with open(path, "w") as f:
                    json.dump(repro, f, indent=2)
                entry["repro_path"] = path
                repros.append(path)
            else:
                repros.append(repro)
            if progress is not None:
                progress.write(
                    f"[chaos] plan {i} FAILED on {eng}; shrunk "
                    f"{len(plan.faults)} -> {len(minimal.faults)} "
                    f"component(s) in {evals} eval(s)\n"
                )
                progress.flush()
        results.append(entry)
    return {
        "seed": seed,
        "plans": results,
        "failures": sum(1 for r in results if not r["ok"]),
        "repros": repros,
    }


def replay_repro(path: str, progress=None) -> InvariantReport:
    """Re-run a shrunk repro artifact's plan on its engine — the
    round-trip that makes the fuzzer's output actionable."""
    with open(path) as f:
        repro = json.load(f)
    if repro.get("schema") != REPRO_SCHEMA:
        raise ValueError(f"{path}: not a {REPRO_SCHEMA} artifact")
    plan = FaultPlan.from_dict(repro["plan"])
    if progress is not None:
        progress.write(
            f"[chaos] replaying {repro['engine']} repro: "
            f"{plan.describe()}\n"
        )
    return RUNNERS[repro["engine"]](plan, seed=int(repro.get("seed", 0)))
