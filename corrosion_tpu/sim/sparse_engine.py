"""Any-node-writes cluster simulation over the rotating-slot writer plane.

The dense engine (sim/engine.py) models W writer streams as fixed tensor
columns; this engine lets ALL N nodes write (the reference's model —
writes originate anywhere, doc/crdts.md:25-28) by multiplexing active
writers onto ``w_hot`` rotating slots (ops/sparse_writers.py):

- The run is split into EPOCHS of ``sparse.epoch_rounds`` rounds. At each
  boundary a host planner retires quiescent slots and promotes newly
  active writers; the device checks feasibility first (zero-lag demotion,
  deviation-table headroom) so bookkeeping is never silently dropped.
- Inside an epoch the unchanged gossip kernels run over the slot axis
  (broadcast + SWIM + anti-entropy sync), plus a gated ``cold_sync`` that
  heals deviation entries left by forced demotions.
- Visibility sampling: samples of currently-hot writers are tracked per
  round on the slot plane; samples of demoted writers resolve at epoch
  granularity against the deviation tables (zero-lag demotion implies
  they were already visible everywhere while hot, so the coarser
  resolution only applies after forced demotions).

Slot exhaustion (more new writers than free + demotable slots) raises —
it would otherwise silently defer commits and corrupt the sampled-write
bookkeeping. Size w_hot to the workload's concurrent-writer envelope; the
failure mode is explicit backpressure, mirroring the admission control a
live agent would apply.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.ops import gossip as gossip_ops
from corrosion_tpu.ops import sparse_writers as sw_ops
from corrosion_tpu.ops import swim as swim_ops
from corrosion_tpu.ops.gossip import GossipConfig, Topology
from corrosion_tpu.ops.sparse_writers import SparseConfig, SparseState
from corrosion_tpu.ops.swim import SwimConfig
from corrosion_tpu.sim import telemetry as telemetry_mod
from corrosion_tpu.sim.engine import Schedule
from corrosion_tpu.sim.telemetry import KernelTelemetry


@dataclass(frozen=True)
class SparseClusterConfig:
    swim: SwimConfig
    gossip: GossipConfig  # n_writers == w_hot slots; track_writer_ids=True
    sparse: SparseConfig
    round_ms: float = 500.0

    def __post_init__(self):
        if not self.gossip.track_writer_ids:
            raise ValueError(
                "sparse engine requires gossip.track_writer_ids=True "
                "(cell keys must follow global writer identity)"
            )

    @property
    def n_nodes(self) -> int:
        return self.gossip.n_nodes

    @property
    def w_hot(self) -> int:
        return self.gossip.n_writers


class _Planner:
    """Host-side slot allocator. Device state is consulted through
    demote_report before any forced retirement is committed."""

    def __init__(self, n: int, w_hot: int, sp: SparseConfig):
        self.n = n
        self.w_hot = w_hot
        self.sp = sp
        self.slot_of = np.full(n, -1, np.int32)  # writer node -> slot
        self.writer_of = np.full(w_hot, -1, np.int32)  # slot -> writer
        self.last_active = np.full(w_hot, -(10**9), np.int64)
        self.free: list[int] = list(range(w_hot))

    def plan(self, epoch: int, writes_ep: np.ndarray, check):
        """writes_ep: [E, N]. ``check(cand_slots, cand_ok)`` runs
        demote_report on device. Returns (retire, promote) host arrays
        (padded to d_max/p_max) for sw_ops.rotate."""
        sp = self.sp
        active = np.nonzero(writes_ep.sum(axis=0))[0]
        new = [int(w) for w in active if self.slot_of[w] < 0]
        active_set = set(int(w) for w in active)

        # Retirement candidates: occupied, writer quiescent long enough,
        # not active this epoch. Most-quiescent first.
        occ = np.nonzero(self.writer_of >= 0)[0]
        cands = [
            int(s)
            for s in occ
            if int(self.writer_of[s]) not in active_set
            and self.last_active[s] <= epoch - sp.demote_after
        ]
        cands.sort(key=lambda s: self.last_active[s])
        cands = cands[: sp.d_max]
        retire: list[int] = []
        diag = {"cands": len(cands), "zero_lag": 0, "forced_pool": 0,
                "take": 0, "f_load_head": []}
        if cands:
            cand_arr = np.full(sp.d_max, 0, np.int32)
            cand_ok = np.zeros(sp.d_max, bool)
            cand_arr[: len(cands)] = cands
            cand_ok[: len(cands)] = True
            caught_up, maxload = check(cand_arr, cand_ok)
            caught_up = np.asarray(caught_up)[: len(cands)]
            # Zero-lag retirements are free — take them all.
            retire = [s for s, c in zip(cands, caught_up) if c]
            diag["zero_lag"] = len(retire)
            shortage = len(new) - (len(self.free) + len(retire))
            if shortage > 0:
                # Forced demotions, only as many as needed and only while
                # every node's deviation table provably has headroom.
                forced_pool = [
                    s for s, c in zip(cands, caught_up) if not c
                ]
                diag["forced_pool"] = len(forced_pool)
                if forced_pool:
                    f_arr = np.full(sp.d_max, 0, np.int32)
                    f_ok = np.zeros(sp.d_max, bool)
                    f_arr[: len(forced_pool)] = forced_pool
                    f_ok[: len(forced_pool)] = True
                    _, f_load = check(f_arr, f_ok)
                    f_load = np.asarray(f_load)[: len(forced_pool)]
                    take = 0
                    while (
                        take < len(forced_pool)
                        and take < shortage
                        and f_load[take] <= sp.k_dev
                    ):
                        take += 1
                    retire += forced_pool[:take]
                    diag["take"] = take
                    diag["f_load_head"] = f_load[:8].tolist()

        free_after = len(self.free) + len(retire)
        if len(new) > free_after:
            raise RuntimeError(
                f"slot exhaustion at epoch {epoch}: {len(new)} new "
                f"writers, {free_after} slots available (w_hot="
                f"{self.w_hot}); size w_hot to the workload's "
                f"concurrent-writer envelope [diag: {diag}]"
            )
        if len(new) > sp.p_max or len(retire) > sp.d_max:
            raise RuntimeError(
                f"epoch {epoch} churn exceeds static pads: "
                f"{len(new)} promotions (p_max={sp.p_max}), "
                f"{len(retire)} retirements (d_max={sp.d_max})"
            )

        # Commit host bookkeeping.
        slots_avail = list(retire) + self.free
        promote_slots, promote_writers = [], []
        for s in retire:
            w_old = int(self.writer_of[s])
            self.slot_of[w_old] = -1
            self.writer_of[s] = -1
        for w in new:
            s = slots_avail.pop(0)
            promote_slots.append(s)
            promote_writers.append(w)
            self.slot_of[w] = s
            self.writer_of[s] = w
        self.free = slots_avail
        for w in active:
            s = self.slot_of[w]
            self.last_active[s] = epoch

        def pad(vals, size, fill=0):
            out = np.full(size, fill, np.int32)
            out[: len(vals)] = vals
            return out

        r = (
            pad(retire, sp.d_max),
            np.arange(sp.d_max) < len(retire),
            pad(promote_slots, sp.p_max),
            pad(promote_writers, sp.p_max),
            np.arange(sp.p_max) < len(promote_slots),
        )
        return r

    def writes_to_slots(self, writes_ep: np.ndarray) -> np.ndarray:
        """[E, N] -> [E, w_hot] via the current slot map."""
        out = np.zeros((writes_ep.shape[0], self.w_hot), writes_ep.dtype)
        occ = np.nonzero(self.writer_of >= 0)[0]
        out[:, occ] = writes_ep[:, self.writer_of[occ]]
        return out

    def topology_arrays(self):
        """(writer_nodes, writer_of_node, writer_ids) for this epoch."""
        wn = np.maximum(self.writer_of, 0).astype(np.int32)
        won = self.slot_of.copy()
        wid = np.maximum(self.writer_of, 0).astype(np.uint32)
        return wn, won, wid

    def snapshot(self) -> dict:
        """Host planner state for checkpoint/resume (sim/checkpoint.py)."""
        return {
            "slot_of": self.slot_of.copy(),
            "writer_of": self.writer_of.copy(),
            "last_active": self.last_active.copy(),
            "free": np.asarray(self.free, np.int32),
        }

    def restore(self, snap: dict) -> None:
        self.slot_of = np.asarray(snap["slot_of"], np.int32).copy()
        self.writer_of = np.asarray(snap["writer_of"], np.int32).copy()
        self.last_active = np.asarray(snap["last_active"], np.int64).copy()
        self.free = [int(x) for x in snap["free"]]


def _epoch_scan_impl(
    sstate: SparseState,
    swim_state,
    vis_round: jax.Array,  # i32[S, N]
    topo: Topology,
    xs,  # (writes_slots [E, W], kill [E, ?], revive [E, ?], round_idx [E],
    #      loss [E, R] | None, probe_loss [E] | None)
    partition: jax.Array,  # bool[E, R, R]
    s_slot: jax.Array,  # i32[S] sample slot this epoch (-1 = cold)
    s_ver: jax.Array,  # u32[S]
    s_round: jax.Array,  # i32[S]
    base_key: jax.Array,
    cfg: SparseClusterConfig,
    sp: SparseConfig,
    has_churn: bool,
    bcast_fn=None,  # static broadcast override (parallel/shard_driver)
):
    swim_impl = swim_ops.impl(cfg.swim)
    region = topo.region
    bfn = gossip_ops.broadcast_round if bcast_fn is None else bcast_fn

    def body(carry, x):
        st, sw, vr = carry
        w_slots, part, kl, rv, r, lo, pl = x
        key = jax.random.fold_in(base_key, r)
        if has_churn:
            k_churn, k_b, k_sw, k_sy, k_rejoin = jax.random.split(key, 5)
            # Pause-resume churn only: the sparse engine degrades
            # crash-with-state-wipe (see gossip.revive_sync's semantics
            # note; simulate_sparse rejects wipe schedules loudly).
            sw = swim_impl.apply_churn(
                sw, kl, rv, k_churn, cfg.swim.max_transmissions
            )
        else:
            k_b, k_sw, k_sy = jax.random.split(key, 3)
        alive = sw.alive

        with jax.named_scope("corro_broadcast"):
            data, bstats = bfn(
                st.data, topo, alive, part, w_slots, k_b, cfg.gossip,
                loss=lo,
            )
        with jax.named_scope("corro_swim"):
            # After churn: revive bumps are rejoins, not flaps.
            inc_pre = sw.incarnation
            sw = swim_impl.swim_round(sw, k_sw, r, cfg.swim, probe_loss=pl)
        with jax.named_scope("corro_sync"):
            data, ssta = gossip_ops.sync_round(
                data, topo, alive, part, r, k_sy, cfg.gossip
            )
            if has_churn:
                data, rsta = gossip_ops.revive_sync(
                    data, topo, alive, part, rv, k_rejoin, cfg.gossip
                )
                ssta = {k: ssta[k] + rsta[k] for k in ssta}
            st = st._replace(data=data)
            st, csta = sw_ops.cold_sync(
                st, region, alive, part, cfg.gossip, sp
            )

        # Hot-plane visibility for samples whose writer holds a slot.
        with jax.named_scope("corro_track"):
            hot = s_slot >= 0
            vis_now = gossip_ops.visibility(
                st.data, jnp.maximum(s_slot, 0), s_ver,
                backend=cfg.gossip.kernel_backend,
            )
            active_s = r >= s_round
            vr_new = jnp.where(
                (vr < 0) & vis_now & (hot & active_s)[:, None], r, vr
            )

        # Convergence health observables. Staleness is measured on the
        # HOT slot plane (head vs contig over the rotating slots); the
        # cold plane's residue is already carried by `need` through
        # cold_need, and demoted writers are zero-lag by rotation
        # feasibility, so hot-plane lag is the whole story between
        # forced demotions.
        with jax.named_scope("corro_health"):
            newly = (vr_new >= 0) & (vr < 0)
            lat_hist = telemetry_mod.delivery_latency_hist(
                r - s_round[:, None], newly
            )
            stale_sum, stale_max = gossip_ops.staleness(st.data)
            false_alarms, undetected = swim_impl.health_counts(sw)
            # Propagation plane over the hot-slot broadcast traffic;
            # rumor ages track the hot-plane samples (cold-plane
            # resolutions happen at epoch granularity outside the scan,
            # exactly like vis_count). Static skip when disabled.
            prop_stats = telemetry_mod.prop_curves(
                cfg.gossip.prop_observe,
                bstats.get("prop_link"),
                bstats.get("prop_useful"),
                bstats.get("prop_dup"),
                r - s_round[:, None],
                newly,
                kills=bstats.get("prop_kills"),
                pulls=bstats.get("prop_pulls"),
            )

        stats = telemetry_mod.round_curves(
            mismatches=swim_impl.mismatches(sw),
            need=gossip_ops.total_need(st.data) + sw_ops.cold_need(st),
            applied_broadcast=bstats["applied_broadcast"],
            applied_sync=ssta["applied_sync"],
            msgs=bstats["msgs"],
            sessions=ssta["sessions"],
            cell_merges=(
                bstats["cell_merges"]
                + ssta["cell_merges"]
                + csta["cold_merges"]
            ),
            window_degraded=bstats["window_degraded"],
            sync_regrant=ssta["sync_regrant"],
            cold_healed=csta["cold_healed"],
            # Hot-plane visibility events only; demoted-writer samples
            # resolve at epoch granularity outside the scan.
            vis_count=jnp.sum(newly, dtype=jnp.uint32),
            staleness_sum=stale_sum,
            staleness_max=stale_max,
            swim_false_alarms=false_alarms,
            swim_undetected_deaths=undetected,
            swim_flaps=jnp.sum(
                sw.incarnation != inc_pre, dtype=jnp.uint32
            ),
            queue_backlog=gossip_ops.queue_backlog(st.data),
            chaos_lost_msgs=bstats["lost_msgs"],
            xshard_bytes_ici=bstats.get(
                "xshard_bytes_ici", jnp.float32(0.0)
            ),
            xshard_bytes_dcn=bstats.get(
                "xshard_bytes_dcn", jnp.float32(0.0)
            ),
            **lat_hist,
            **prop_stats,
        )
        return (st, sw, vr_new), stats

    (sstate, swim_state, vis_round), curves = jax.lax.scan(
        body,
        (sstate, swim_state, vis_round),
        (xs[0], partition, xs[1], xs[2], xs[3], xs[4], xs[5]),
    )
    return sstate, swim_state, vis_round, curves


# Donated twin: the carried (sstate, swim, vis_round) pytrees alias into
# the outputs so each epoch's state round-trips in place instead of
# copying. It is the driver's ONLY scan entry (a second non-donating
# compile would double the dominant cost of every first epoch); the
# first epoch's carry is made donatable by one deep copy — init arrays
# can share constant buffers, and a caller's resume snapshot must stay
# replayable — amortized over the run. docs/PERFORMANCE.md ("Donation
# invariants"); the plain entry remains for ad-hoc callers.
_epoch_scan = partial(
    jax.jit, static_argnames=("cfg", "sp", "has_churn", "bcast_fn")
)(_epoch_scan_impl)
_epoch_scan_donated = partial(
    jax.jit, static_argnames=("cfg", "sp", "has_churn", "bcast_fn"),
    donate_argnums=(0, 1, 2),
)(_epoch_scan_impl)


@jax.jit
def _cold_vis_update(
    sstate: SparseState,
    vis_round: jax.Array,  # i32[S, N]
    s_writer: jax.Array,  # i32[S] global writer ids
    s_ver: jax.Array,
    s_cold: jax.Array,  # bool[S] writer demoted AND sample committed
    round_now: jax.Array,  # i32
):
    vis = sw_ops.cold_visibility(sstate, s_writer, s_ver)
    return jnp.where(
        (vis_round < 0) & vis & s_cold[:, None], round_now, vis_round
    )


def initial_resume(cfg: SparseClusterConfig, n_samples: int) -> dict:
    """An epoch-0 resume point: lets callers place/shard the device
    arrays (parallel/mesh.shard_sparse_state) before the run starts."""
    planner = _Planner(cfg.n_nodes, cfg.w_hot, cfg.sparse)
    return {
        "planner": planner.snapshot(),
        "sstate": sw_ops.init_sparse(cfg.gossip, cfg.sparse),
        "swim": swim_ops.impl(cfg.swim).init_state(cfg.swim),
        "vis_round": jnp.full((n_samples, cfg.n_nodes), -1, jnp.int32),
        "next_epoch": 0,
    }


def simulate_sparse(
    cfg: SparseClusterConfig,
    topo_base: Topology,
    schedule: Schedule,  # writes [rounds, N] — every node may write
    seed: int = 0,
    resume: dict | None = None,
    stop_after_epoch: int | None = None,
    telemetry: KernelTelemetry | None = None,
    bcast_fn=None,
):
    """Run the epoch-rotated any-node-writes simulation. Returns
    (final_sparse_state, swim_state, vis_round, curves, info).

    ``resume`` (from ``make_resume``) continues a previous run from its
    next epoch: device state + host planner snapshot + epoch cursor. The
    per-round RNG folds the absolute round index, so save/resume is
    bit-identical to an uninterrupted run (tests assert it).

    ``telemetry`` (sim.telemetry.KernelTelemetry) treats every epoch as
    a chunk boundary: the epoch scan is timed and spanned, its per-round
    curves flush to the flight recorder, and run totals fold into the
    metrics registry as ``corro_kernel_*`` series."""
    sp = cfg.sparse
    n = cfg.n_nodes
    rounds = schedule.rounds
    e_len = sp.epoch_rounds
    if schedule.writes.shape[1] != n:
        raise ValueError(
            f"sparse schedule writes must be [rounds, n_nodes], got "
            f"{schedule.writes.shape}"
        )
    if schedule.wipe is not None:
        raise ValueError(
            "the sparse engine does not support crash-with-state-wipe: a "
            "total wipe exceeds its bounded deviation tables (see "
            "gossip.revive_sync). Compile the fault plan with "
            "allow_wipe=False to degrade wipe to pause-resume churn."
        )
    has_churn = schedule.kill is not None or schedule.revive is not None
    n_regions = int(np.asarray(topo_base.region).max()) + 1

    planner = _Planner(n, cfg.w_hot, sp)
    sstate = sw_ops.init_sparse(cfg.gossip, sp)
    swim_state = swim_ops.impl(cfg.swim).init_state(cfg.swim)
    n_samples = len(schedule.sample_writer)
    vis_round = jnp.full((n_samples, n), -1, jnp.int32)
    start_epoch = 0
    if resume is not None:
        planner.restore(resume["planner"])
        sstate = resume["sstate"]
        swim_state = resume["swim"]
        vis_round = resume["vis_round"]
        start_epoch = int(resume["next_epoch"])
    s_writer = jnp.asarray(schedule.sample_writer)
    s_ver = jnp.asarray(schedule.sample_ver)
    s_round_np = schedule.sample_round
    s_round = jnp.asarray(s_round_np)
    base_key = jax.random.PRNGKey(seed)

    def check(cand, ok):
        cu, ml = sw_ops.demote_report(
            sstate, jnp.asarray(cand), jnp.asarray(ok)
        )
        return np.asarray(cu), np.asarray(ml)

    curve_parts = []
    info = {"epochs": 0, "retired": 0, "promoted": 0, "dev_dropped": 0,
            "max_dev_entries": 0}
    # The first epoch's carry is made donatable by one deep copy (init
    # arrays can share constant buffers — XLA rejects a double donation —
    # and a resume snapshot must stay replayable: tests resume twice from
    # one dict). From epoch 1 on the carry is the previous scan's output,
    # owned by construction.
    owned = False
    for e0 in range(start_epoch * e_len, rounds, e_len):
        e1 = min(e0 + e_len, rounds)
        epoch = e0 // e_len
        w_ep = schedule.writes[e0:e1]
        plan = planner.plan(epoch, w_ep, check)
        sstate, rstats = sw_ops.rotate(
            sstate,
            jnp.asarray(plan[0]), jnp.asarray(plan[1]),
            jnp.asarray(plan[2]), jnp.asarray(plan[3]),
            jnp.asarray(plan[4]),
            cfg.gossip,
        )
        dropped = int(rstats["dev_dropped"])
        if dropped:
            raise RuntimeError(
                f"rotate dropped {dropped} deviation entries at epoch "
                f"{epoch} — demote_report feasibility was violated"
            )
        info["epochs"] += 1
        info["retired"] += int(rstats["retired"])
        info["promoted"] += int(rstats["promoted"])
        info["max_dev_entries"] = max(
            info["max_dev_entries"], int(rstats["dev_entries"])
        )

        wn, won, wid = planner.topology_arrays()
        topo = topo_base._replace(
            writer_nodes=jnp.asarray(wn),
            writer_of_node=jnp.asarray(won),
            writer_ids=jnp.asarray(wid),
        )
        writes_slots = jnp.asarray(
            planner.writes_to_slots(w_ep), dtype=jnp.uint32
        )
        el = e1 - e0
        if has_churn:
            zeros_n = np.zeros((el, n), bool)
            kill = jnp.asarray(
                schedule.kill[e0:e1] if schedule.kill is not None else zeros_n
            )
            revive = jnp.asarray(
                schedule.revive[e0:e1]
                if schedule.revive is not None else zeros_n
            )
        else:
            kill = revive = jnp.zeros((el, 1), bool)
        if schedule.partition is not None:
            part = jnp.asarray(schedule.partition[e0:e1])
        else:
            part = jnp.zeros((el, n_regions, n_regions), bool)
        loss_e = (
            None if schedule.loss is None
            else jnp.asarray(schedule.loss[e0:e1], jnp.float32)
        )
        probe_e = (
            None if schedule.probe_loss is None
            else jnp.asarray(schedule.probe_loss[e0:e1], jnp.float32)
        )
        s_slot = jnp.asarray(
            planner.slot_of[np.asarray(schedule.sample_writer)]
            if n_samples else np.zeros(0, np.int32)
        )
        ridx = jnp.arange(e0, e1, dtype=jnp.int32)

        if not owned:
            sstate = telemetry_mod.owned_copy(sstate)
            swim_state = telemetry_mod.owned_copy(swim_state)
            vis_round = telemetry_mod.owned_copy(vis_round)
        if telemetry is None:
            sstate, swim_state, vis_round, curves = _epoch_scan_donated(
                sstate, swim_state, vis_round, topo,
                (writes_slots, kill, revive, ridx, loss_e, probe_e), part,
                s_slot, s_ver, s_round, base_key, cfg, sp, has_churn,
                bcast_fn=bcast_fn,
            )
        else:
            # Epoch boundary == chunk boundary for the flight recorder.
            def _run(sstate=sstate, swim_state=swim_state,
                     vis_round=vis_round, topo=topo,
                     writes_slots=writes_slots, kill=kill, revive=revive,
                     ridx=ridx, part=part, s_slot=s_slot,
                     loss_e=loss_e, probe_e=probe_e):
                out = _epoch_scan_donated(
                    sstate, swim_state, vis_round, topo,
                    (writes_slots, kill, revive, ridx, loss_e, probe_e),
                    part,
                    s_slot, s_ver, s_round, base_key, cfg, sp, has_churn,
                    bcast_fn=bcast_fn,
                )
                return out[:3], out[3]

            (sstate, swim_state, vis_round), curves = telemetry.run_chunk(
                e0, _run
            )
        owned = True
        curve_parts.append({k: np.asarray(v) for k, v in curves.items()})

        # Epoch-end cold visibility at epoch granularity (exact for
        # zero-lag demotions: those were visible everywhere while hot).
        if n_samples:
            s_cold = jnp.asarray(
                (planner.slot_of[np.asarray(schedule.sample_writer)] < 0)
                & (s_round_np <= e1 - 1)
            )
            vis_round = _cold_vis_update(
                sstate, vis_round, s_writer, s_ver, s_cold,
                jnp.int32(e1 - 1),
            )
        if stop_after_epoch is not None and epoch >= stop_after_epoch:
            break

    # A zero-epoch run (resume cursor already at/past the schedule end,
    # or rounds == 0) executes no epochs: return the resumed state with
    # EMPTY curves instead of tripping over curve_parts[0].
    merged = (
        {
            k: np.concatenate([p[k] for p in curve_parts])
            for k in curve_parts[0]
        }
        if curve_parts
        else {}
    )
    if telemetry is not None and curve_parts:
        telemetry.on_run_end(merged)
    info["resume"] = {
        "planner": planner.snapshot(),
        "sstate": sstate,
        "swim": swim_state,
        "vis_round": vis_round,
        "next_epoch": info["epochs"] + start_epoch,
    }
    return sstate, swim_state, vis_round, merged, info


def final_head_full(sstate: SparseState) -> np.ndarray:
    """head_full with the still-hot slots written back — the global
    committed head per node at end of run."""
    hf = np.asarray(sstate.head_full).copy()
    slot_writer = np.asarray(sstate.slot_writer)
    head = np.asarray(sstate.data.head)
    occ = slot_writer >= 0
    hf[slot_writer[occ]] = head[occ]
    return hf


def converged_sparse(sstate: SparseState) -> bool:
    """Hot slots at head everywhere + no deviation entries."""
    slot_writer = np.asarray(sstate.slot_writer)
    occ = slot_writer >= 0
    contig = np.asarray(sstate.data.contig)[:, occ]
    head = np.asarray(sstate.data.head)[occ]
    hot_ok = bool((contig == head[None, :]).all())
    dev_ok = not bool(np.asarray(sstate.dev_any))
    return hot_ok and dev_ok
