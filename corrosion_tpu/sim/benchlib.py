"""Shared bench harness pieces for bench.py and scripts/bench_smoke.py.

One place owns the plane-attribution composite (the cumulative-prefix
stage timing bench.py documents) and the budget-gate arithmetic the CI
bench-smoke job applies, so the headline bench and the regression gate
can never drift onto different measurement paths — the r04→r05 class of
silent regression slipped through exactly because nothing in CI measured
step time at all (docs/PERFORMANCE.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# Execution order of the composite's stages — must mirror cluster_round.
PLANE_STAGES = ("broadcast", "swim", "sync", "track")
# Gate tolerance applied when a budget file omits the key — the same
# default --update writes, so a hand-edited budget never silently gates
# tighter than the documented workflow.
DEFAULT_TOLERANCE = 1.5


def get_path(measured: dict, dotted: str):
    """Dotted-path lookup into a nested measurement dict (None when any
    segment is missing) — the budget gates' shared ceiling resolver
    (serving + fidelity; see their ``check_*_budget``)."""
    cur = measured
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def config_fingerprint(*parts) -> str:
    """Stable short hash of the measured configuration. Dataclass /
    NamedTuple reprs are deterministic (field order is declaration
    order), so two runs fingerprint equal iff every config field and
    bench shape parameter matches — the provenance field
    ``telemetry.check_bench_invariants`` requires on every emitted
    bench JSON."""
    import hashlib

    h = hashlib.sha256()
    for p in parts:
        h.update(repr(p).encode())
        h.update(b"\x00")
    return h.hexdigest()[:16]


def bench_context(*fingerprint_parts) -> dict:
    """The self-describing provenance block every bench emit site must
    include (and check_bench_invariants asserts): the platform the
    numbers were actually measured on, the device count, and the config
    fingerprint — so a CPU-fallback run can never again be mistaken for
    an accelerator artifact (the BENCH_r05 incident)."""
    devs = jax.devices()
    return {
        "platform": devs[0].platform,
        "device_count": len(devs),
        "config_fingerprint": config_fingerprint(*fingerprint_parts),
    }


def rounded_step_report(step_ms: float, plane: dict) -> dict:
    """The ONE emit-site rounding: round step and planes to 0.1 ms and
    derive the residual from the ROUNDED values, so
    ``sum(plane_ms) + residual_ms == step_ms`` holds exactly on the
    published numbers (telemetry.check_bench_invariants re-asserts it).
    Shared by bench.py and scripts/bench_smoke.py — two hand-rolled
    copies of this arithmetic is how the emitted invariants drift."""
    step_r = round(step_ms, 1)
    plane_r = {k: round(v, 1) for k, v in plane.items()}
    return {
        "step_ms": step_r,
        "plane_ms": plane_r,
        "residual_ms": round(step_r - sum(plane_r.values()), 1),
    }


def roofline_report(stage_costs: dict, plane_ms: dict) -> dict:
    """Join the cost model's per-stage flops/bytes
    (``obs.costs.roofline_stage_costs``) with the measured (already
    emit-rounded) ``plane_ms`` into the roofline block every bench JSON
    carries: achieved FLOP/s, B/s, and arithmetic intensity per plane.
    Rates are derived FROM the emitted numbers — flops / (plane_ms/1e3)
    — so ``telemetry.check_bench_invariants`` can recompute them
    exactly; a plane measured at 0.0 ms publishes null rates rather
    than infinities."""
    out = {}
    for name, ms in plane_ms.items():
        cost = stage_costs.get(name, {"flops": 0.0, "bytes": 0.0})
        flops = float(cost["flops"])
        nbytes = float(cost["bytes"])
        out[name] = {
            "flops": flops,
            "bytes": nbytes,
            "flops_per_s": (flops / (ms / 1000.0)) if ms else None,
            "bytes_per_s": (nbytes / (ms / 1000.0)) if ms else None,
            "intensity": round(flops / nbytes, 4) if nbytes else None,
        }
    return out


def compile_split_report(first_run_s: float, compile_ms: float) -> dict:
    """The ledger split of the first-run blob, derived from the ROUNDED
    values so ``compile_ms + first_step_ms == first_run_incl_compile_s
    * 1000`` holds exactly on the published numbers (same emit-site
    rounding rule as :func:`rounded_step_report`). ``first_step_ms`` is
    the first run's non-compile wall: device execution plus host
    dispatch — everything the blob contained that was not XLA
    compilation."""
    first_run_r = round(first_run_s, 1)
    compile_r = round(min(compile_ms, first_run_r * 1000.0), 1)
    return {
        "first_run_incl_compile_s": first_run_r,
        "compile_ms": compile_r,
        "first_step_ms": round(first_run_r * 1000.0 - compile_r, 1),
    }


def plane_composite(cfg, topo, sched, final, bcast_fn=None):
    """Build the cumulative-prefix attribution inputs for a finished run.

    Returns ``(make_step, stages, carry0)`` for
    ``telemetry.attribute_planes``: a composite round step over the run's
    FINAL state (fresh state would flatter sync — no deficits to score or
    grant) whose stages enable one at a time in execution order.

    ``bcast_fn`` swaps the broadcast stage's driver exactly like the
    engine scan bodies do — the multi-chip lane passes
    ``parallel.shard_driver.make_sharded_broadcast(mesh)`` (with
    ``final``/``topo`` already placed on the mesh) so the attributed
    broadcast cost is the SHARDED delivery chain including its explicit
    queue exchange, not the single-host form.

    NOTE: the big arrays ride the CARRY, never closures — a closed-over
    DataState would be embedded as compile-payload constants (hundreds of
    MB at 10k; the axon compile tunnel rejects it outright).
    """
    from corrosion_tpu.ops import gossip as gossip_ops
    from corrosion_tpu.ops import swim as swim_ops

    if bcast_fn is None:
        bcast_fn = gossip_ops.broadcast_round
    swim_impl = swim_ops.impl(cfg.swim)
    n_regions = int(np.asarray(topo.region).max()) + 1
    part = jnp.zeros((n_regions, n_regions), bool)
    writes = jnp.asarray(sched.writes[0], jnp.uint32)
    key = jax.random.PRNGKey(0)
    s_writer = jnp.asarray(sched.sample_writer)
    s_ver = jnp.asarray(sched.sample_ver)
    s_round = jnp.asarray(sched.sample_round)

    def composite(enabled):
        def step(carry, i):
            d, sw, vr = carry
            k = jax.random.fold_in(key, i)
            k_b, k_sw, k_sy = jax.random.split(k, 3)
            if "broadcast" in enabled:
                d, _ = bcast_fn(
                    d, topo, sw.alive, part, writes, k_b, cfg.gossip
                )
            if "swim" in enabled:
                sw = swim_impl.swim_round(sw, k_sw, i, cfg.swim)
            if "sync" in enabled:
                d, _ = gossip_ops.sync_round(
                    d, topo, sw.alive, part, i, k_sy, cfg.gossip
                )
            if "track" in enabled:
                vis_now = gossip_ops.visibility(
                    d, s_writer, s_ver,
                    backend=cfg.gossip.kernel_backend,
                )
                active = i >= s_round
                vr = jnp.where(
                    (vr < 0) & vis_now & active[:, None], i, vr
                )
                need = gossip_ops.total_need(d)
                vr = vr + (need * jnp.uint32(0)).astype(vr.dtype)
            return d, sw, vr

        return step

    carry0 = (final.data, final.swim, final.vis_round)
    return composite, PLANE_STAGES, carry0


# Multichip lane fixed shape (scripts/multichip_smoke.py + bench.py
# --multichip): big enough that the broadcast queue exchange moves real
# bytes, small enough that 4 device counts x 2 planes compile inside a
# CI runner's budget.
MULTICHIP_DEVICE_COUNTS = (1, 2, 4, 8)
MULTICHIP_NODES = 512
MULTICHIP_ROUNDS = 32
MULTICHIP_SPARSE_NODES = 256
MULTICHIP_SEED = 0
# The O(N/D) acceptance bound: the max per-device live-state bytes at
# D=8 must be at most this fraction of the D=1 state (1/8 sharded +
# replicated writer heads and slot metadata leaves headroom to ~1/6).
MULTICHIP_STATE_FRACTION = 1.0 / 6.0


def multichip_mesh(d: int):
    """The lane's mesh for a device count: 2-D (dcn, ici) from 4 devices
    up — so the coalesced outer hop of the queue exchange is exercised,
    not just the fast axis — else the 1-D node mesh."""
    from corrosion_tpu import parallel

    if d >= 4:
        return parallel.make_wan_mesh(2, d // 2)
    return parallel.make_mesh(d)


def measure_multichip(
    device_counts=MULTICHIP_DEVICE_COUNTS,
    large_nodes: int | None = None,
    large_rounds: int = 96,
    progress=None,
) -> dict:
    """Measure the multi-chip lane: dense + sparse planes under the
    explicit shard_map round driver at every requested device count.

    Per device count D: warm per-round ``step_ms`` for both planes (the
    SAME driver at D=1 anchors the scaling curve — shard_map over a
    1-device mesh runs the identical code path with identity
    collectives). At max(D) additionally: the cumulative-prefix plane
    split measured ON THE SHARDED step (``plane_composite`` with the
    sharded broadcast), the exchange's cross-shard bytes per round
    (curves vs the static :func:`traffic_model` — they must agree
    exactly), max per-device live-state MiB vs the D=1 state bytes
    (the measured O(N/D) claim), and dense convergence. Final states
    and curves are asserted bit-identical across every device count —
    a multichip artifact can never publish numbers from diverged runs.

    ``large_nodes`` appends the "largest sharded run" tail: a dense
    convergence run at that node count on the max-D mesh, reported
    under ``large`` (step_ms, per-device state MiB, converged).

    Returns the self-describing report dict (caller emits it through
    ``telemetry.check_bench_invariants``).
    """
    import time

    from corrosion_tpu import models, parallel
    from corrosion_tpu.models.baselines import anywrite_sparse
    from corrosion_tpu.ops import onehot
    from corrosion_tpu.sim import telemetry

    def note(msg):
        if progress is not None:
            progress.write(f"[multichip] {msg}\n")
            progress.flush()

    cfg, topo, sched = models.merge_10k(
        n=MULTICHIP_NODES, rounds=MULTICHIP_ROUNDS, samples=64
    )
    s_cfg, s_topo, s_sched = anywrite_sparse(
        n=MULTICHIP_SPARSE_NODES, w_hot=16, rounds=MULTICHIP_ROUNDS,
        n_regions=4, epoch_rounds=8, cohort=10, burst_writes=2,
        samples=16, k_dev=8,
    )
    dmax = max(device_counts)
    report: dict = {}
    ref_contig = ref_curves = None
    s_ref = None
    state_mib: dict = {}
    for d in sorted(device_counts):
        mesh = multichip_mesh(d)
        note(f"D={d}: dense compile+run")
        final, curves = parallel.simulate_sharded(
            cfg, topo, sched, mesh, seed=MULTICHIP_SEED
        )
        jax.block_until_ready(final.data.contig)
        t0 = time.perf_counter()
        final, curves = parallel.simulate_sharded(
            cfg, topo, sched, mesh, seed=MULTICHIP_SEED
        )
        jax.block_until_ready(final.data.contig)
        step_ms = (
            (time.perf_counter() - t0) / MULTICHIP_ROUNDS * 1000.0
        )
        contig = np.asarray(final.data.contig)
        if ref_contig is None:
            ref_contig, ref_curves = contig, curves
        else:
            np.testing.assert_array_equal(
                contig, ref_contig,
                err_msg=f"dense final state diverged at D={d}",
            )
            for k in ref_curves:
                if k.startswith("xshard"):
                    continue
                np.testing.assert_array_equal(
                    ref_curves[k], curves[k],
                    err_msg=f"dense curve {k} diverged at D={d}",
                )
        per_dev = parallel.per_device_state_bytes(final)
        state_mib[d] = max(per_dev.values()) / 2**20
        note(f"D={d}: sparse compile+run")
        s_final = parallel.simulate_sparse_sharded(
            s_cfg, s_topo, s_sched, mesh, seed=MULTICHIP_SEED
        )
        jax.block_until_ready(s_final[0].data.contig)
        t0 = time.perf_counter()
        s_final = parallel.simulate_sparse_sharded(
            s_cfg, s_topo, s_sched, mesh, seed=MULTICHIP_SEED
        )
        jax.block_until_ready(s_final[0].data.contig)
        s_step_ms = (
            (time.perf_counter() - t0) / MULTICHIP_ROUNDS * 1000.0
        )
        if s_ref is None:
            s_ref = np.asarray(s_final[0].data.contig)
        else:
            np.testing.assert_array_equal(
                np.asarray(s_final[0].data.contig), s_ref,
                err_msg=f"sparse final state diverged at D={d}",
            )
        sfx = "" if d == dmax else f"_d{d}"
        if d == dmax:
            # Plane split measured ON the sharded step: the composite's
            # broadcast stage is the shard_map delivery chain including
            # its explicit queue exchange.
            note(f"D={d}: plane attribution")
            bfn = parallel.make_sharded_broadcast(mesh)
            composite, stages, carry0 = plane_composite(
                cfg, parallel.replicate(topo, mesh), sched, final,
                bcast_fn=bfn,
            )
            attr = telemetry.attribute_planes(
                composite, stages, carry0, iters=10
            )
            plane, _ = attr.scale(step_ms)
            report.update(rounded_step_report(step_ms, plane))
            # Roofline on the SAME sharded composite: per-device
            # flops/bytes per stage under the shard_map delivery chain
            # (cost_analysis of an SPMD executable is per device),
            # joined with the measured plane split.
            from corrosion_tpu.obs import costs as costs_mod

            report["roofline"] = roofline_report(
                costs_mod.roofline_stage_costs(composite, stages, carry0),
                report["plane_ms"],
            )
            tm = parallel.traffic_model(cfg.gossip, mesh)
            got_ici = float(curves["xshard_bytes_ici"][0])
            got_dcn = float(curves["xshard_bytes_dcn"][0])
            if (got_ici, got_dcn) != (
                tm["xshard_bytes_ici"], tm["xshard_bytes_dcn"]
            ):
                raise AssertionError(
                    f"measured cross-shard bytes ({got_ici}, {got_dcn}) "
                    f"!= static traffic model ({tm['xshard_bytes_ici']},"
                    f" {tm['xshard_bytes_dcn']})"
                )
            heads = np.asarray(final.data.head)
            report.update(
                {
                    "xshard_bytes_per_round_ici": got_ici,
                    "xshard_bytes_per_round_dcn": got_dcn,
                    "traffic_model": tm["detail"],
                    "converged": bool((contig == heads[None, :]).all()),
                }
            )
        else:
            report[f"step_ms{sfx}"] = round(step_ms, 1)
        report[f"step_ms_sparse{sfx or '_d' + str(d)}"] = round(
            s_step_ms, 1
        )
    frac = state_mib[dmax] / state_mib[min(device_counts)]
    report.update(
        {
            **bench_context(
                cfg, s_cfg, MULTICHIP_NODES, MULTICHIP_ROUNDS,
                MULTICHIP_SEED, tuple(sorted(device_counts)),
            ),
            "kernels": onehot.resolve_backend(cfg.gossip.kernel_backend),
            "metric": "multichip_step_scaling",
            "nodes": MULTICHIP_NODES,
            "sparse_nodes": MULTICHIP_SPARSE_NODES,
            "rounds": MULTICHIP_ROUNDS,
            "seed": MULTICHIP_SEED,
            "device_counts": sorted(device_counts),
            "device_count": dmax,
            "state_mib_per_device": {
                f"d{d}": round(v, 3) for d, v in state_mib.items()
            },
            "state_fraction_dmax": round(frac, 4),
            "bit_identical_across_device_counts": True,
        }
    )
    if len(device_counts) > 1 and frac > MULTICHIP_STATE_FRACTION:
        raise AssertionError(
            f"per-device state at D={dmax} holds {frac:.3f} of the "
            f"D={min(device_counts)} state bytes — O(N/D) sharding "
            f"requires <= {MULTICHIP_STATE_FRACTION:.3f}"
        )
    if large_nodes:
        note(f"large: {large_nodes} nodes on D={dmax}")
        report["large"] = _measure_large(
            large_nodes, large_rounds, multichip_mesh(dmax), note
        )
    return report


def _measure_large(n_nodes: int, rounds: int, mesh, note) -> dict:
    """The 'largest sharded run the host can hold' tail: a dense
    convergence run at ``n_nodes`` on the lane's max mesh — light early
    writes, then drain (the dryrun's schedule shape), queue depth 16
    (wall-clock fidelity note in __graft_entry__.dryrun_multichip)."""
    import time
    from dataclasses import replace as dc_replace

    from corrosion_tpu import models, parallel

    n_writers = min(128, n_nodes // 4)
    cfg, topo, sched = models.wan_100k(
        n=n_nodes, n_regions=8, n_writers=n_writers, rounds=rounds,
        samples=16, partition=False,
    )
    cfg = dc_replace(cfg, gossip=dc_replace(cfg.gossip, queue=16))
    sched.writes[:, :] = 0
    sched.writes[:6, :] = 1
    sched = sched.make_samples(16)
    t0 = time.perf_counter()
    final, curves = parallel.simulate_sharded(
        cfg, topo, sched, mesh, seed=MULTICHIP_SEED
    )
    jax.block_until_ready(final.data.contig)
    wall = time.perf_counter() - t0
    heads = np.asarray(final.data.head)
    per_dev = parallel.per_device_state_bytes(final)
    note(f"large: {wall:.0f}s wall, need={int(curves['need'][-1])}")
    return {
        "nodes": n_nodes,
        "rounds": rounds,
        "step_ms_incl_compile": round(wall / rounds * 1000.0, 1),
        "converged": bool(
            (np.asarray(final.data.contig) == heads[None, :]).all()
        ),
        "need_last": int(curves["need"][-1]),
        "state_mib_per_device_max": round(
            max(per_dev.values()) / 2**20, 2
        ),
        "xshard_bytes_per_round_ici": float(
            curves["xshard_bytes_ici"][0]
        ),
        "xshard_bytes_per_round_dcn": float(
            curves["xshard_bytes_dcn"][0]
        ),
    }


def check_budget(
    measured: dict, budget: dict
) -> tuple[bool, list[str]]:
    """Gate a measured ``{step_ms, plane_ms:{...}}`` report against a
    committed budget file (bench_budget.json).

    The budget carries per-key millisecond ceilings plus a ``tolerance``
    multiplier absorbing machine-to-machine variance; a key breaches when
    ``measured > budget_ms * tolerance``. Returns ``(ok, breaches)`` with
    one human-readable line per breach. Budget keys absent from the
    measurement are breaches too (a silently vanished plane is how the
    r05 regression class hides), and so is a bench-shape mismatch: a
    measurement taken at different ``nodes``/``rounds``/``platform``/
    ``kernels`` than the budget was refreshed at must not gate against
    stale ceilings (shrinking the smoke config without ``--update``
    would silently loosen the gate; ceilings measured on one platform
    or kernel backend say nothing about another).
    """
    tol = float(budget.get("tolerance", DEFAULT_TOLERANCE))
    breaches: list[str] = []
    for dim in ("nodes", "rounds", "platform", "kernels", "device_count"):
        if dim in budget and measured.get(dim) != budget[dim]:
            breaches.append(
                f"{dim}: measured at {measured.get(dim)} but the budget "
                f"was refreshed at {budget[dim]} — rerun with --update"
            )

    def gate(name: str, got, limit) -> None:
        if got is None:
            breaches.append(f"{name}: missing from measurement")
        elif float(got) > float(limit) * tol:
            breaches.append(
                f"{name}: {float(got):.1f} ms > budget "
                f"{float(limit):.1f} ms x{tol}"
            )

    gate("step_ms", measured.get("step_ms"), budget["step_ms"])
    for plane, limit in budget.get("plane_ms", {}).items():
        gate(
            f"plane_ms.{plane}",
            measured.get("plane_ms", {}).get(plane),
            limit,
        )
    return not breaches, breaches
