"""Shared bench harness pieces for bench.py and scripts/bench_smoke.py.

One place owns the plane-attribution composite (the cumulative-prefix
stage timing bench.py documents) and the budget-gate arithmetic the CI
bench-smoke job applies, so the headline bench and the regression gate
can never drift onto different measurement paths — the r04→r05 class of
silent regression slipped through exactly because nothing in CI measured
step time at all (docs/PERFORMANCE.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# Execution order of the composite's stages — must mirror cluster_round.
PLANE_STAGES = ("broadcast", "swim", "sync", "track")
# Gate tolerance applied when a budget file omits the key — the same
# default --update writes, so a hand-edited budget never silently gates
# tighter than the documented workflow.
DEFAULT_TOLERANCE = 1.5


def config_fingerprint(*parts) -> str:
    """Stable short hash of the measured configuration. Dataclass /
    NamedTuple reprs are deterministic (field order is declaration
    order), so two runs fingerprint equal iff every config field and
    bench shape parameter matches — the provenance field
    ``telemetry.check_bench_invariants`` requires on every emitted
    bench JSON."""
    import hashlib

    h = hashlib.sha256()
    for p in parts:
        h.update(repr(p).encode())
        h.update(b"\x00")
    return h.hexdigest()[:16]


def bench_context(*fingerprint_parts) -> dict:
    """The self-describing provenance block every bench emit site must
    include (and check_bench_invariants asserts): the platform the
    numbers were actually measured on, the device count, and the config
    fingerprint — so a CPU-fallback run can never again be mistaken for
    an accelerator artifact (the BENCH_r05 incident)."""
    devs = jax.devices()
    return {
        "platform": devs[0].platform,
        "device_count": len(devs),
        "config_fingerprint": config_fingerprint(*fingerprint_parts),
    }


def rounded_step_report(step_ms: float, plane: dict) -> dict:
    """The ONE emit-site rounding: round step and planes to 0.1 ms and
    derive the residual from the ROUNDED values, so
    ``sum(plane_ms) + residual_ms == step_ms`` holds exactly on the
    published numbers (telemetry.check_bench_invariants re-asserts it).
    Shared by bench.py and scripts/bench_smoke.py — two hand-rolled
    copies of this arithmetic is how the emitted invariants drift."""
    step_r = round(step_ms, 1)
    plane_r = {k: round(v, 1) for k, v in plane.items()}
    return {
        "step_ms": step_r,
        "plane_ms": plane_r,
        "residual_ms": round(step_r - sum(plane_r.values()), 1),
    }


def plane_composite(cfg, topo, sched, final):
    """Build the cumulative-prefix attribution inputs for a finished run.

    Returns ``(make_step, stages, carry0)`` for
    ``telemetry.attribute_planes``: a composite round step over the run's
    FINAL state (fresh state would flatter sync — no deficits to score or
    grant) whose stages enable one at a time in execution order.

    NOTE: the big arrays ride the CARRY, never closures — a closed-over
    DataState would be embedded as compile-payload constants (hundreds of
    MB at 10k; the axon compile tunnel rejects it outright).
    """
    from corrosion_tpu.ops import gossip as gossip_ops
    from corrosion_tpu.ops import swim as swim_ops

    swim_impl = swim_ops.impl(cfg.swim)
    n_regions = int(np.asarray(topo.region).max()) + 1
    part = jnp.zeros((n_regions, n_regions), bool)
    writes = jnp.asarray(sched.writes[0], jnp.uint32)
    key = jax.random.PRNGKey(0)
    s_writer = jnp.asarray(sched.sample_writer)
    s_ver = jnp.asarray(sched.sample_ver)
    s_round = jnp.asarray(sched.sample_round)

    def composite(enabled):
        def step(carry, i):
            d, sw, vr = carry
            k = jax.random.fold_in(key, i)
            k_b, k_sw, k_sy = jax.random.split(k, 3)
            if "broadcast" in enabled:
                d, _ = gossip_ops.broadcast_round(
                    d, topo, sw.alive, part, writes, k_b, cfg.gossip
                )
            if "swim" in enabled:
                sw = swim_impl.swim_round(sw, k_sw, i, cfg.swim)
            if "sync" in enabled:
                d, _ = gossip_ops.sync_round(
                    d, topo, sw.alive, part, i, k_sy, cfg.gossip
                )
            if "track" in enabled:
                vis_now = gossip_ops.visibility(
                    d, s_writer, s_ver,
                    backend=cfg.gossip.kernel_backend,
                )
                active = i >= s_round
                vr = jnp.where(
                    (vr < 0) & vis_now & active[:, None], i, vr
                )
                need = gossip_ops.total_need(d)
                vr = vr + (need * jnp.uint32(0)).astype(vr.dtype)
            return d, sw, vr

        return step

    carry0 = (final.data, final.swim, final.vis_round)
    return composite, PLANE_STAGES, carry0


def check_budget(
    measured: dict, budget: dict
) -> tuple[bool, list[str]]:
    """Gate a measured ``{step_ms, plane_ms:{...}}`` report against a
    committed budget file (bench_budget.json).

    The budget carries per-key millisecond ceilings plus a ``tolerance``
    multiplier absorbing machine-to-machine variance; a key breaches when
    ``measured > budget_ms * tolerance``. Returns ``(ok, breaches)`` with
    one human-readable line per breach. Budget keys absent from the
    measurement are breaches too (a silently vanished plane is how the
    r05 regression class hides), and so is a bench-shape mismatch: a
    measurement taken at different ``nodes``/``rounds``/``platform``/
    ``kernels`` than the budget was refreshed at must not gate against
    stale ceilings (shrinking the smoke config without ``--update``
    would silently loosen the gate; ceilings measured on one platform
    or kernel backend say nothing about another).
    """
    tol = float(budget.get("tolerance", DEFAULT_TOLERANCE))
    breaches: list[str] = []
    for dim in ("nodes", "rounds", "platform", "kernels"):
        if dim in budget and measured.get(dim) != budget[dim]:
            breaches.append(
                f"{dim}: measured at {measured.get(dim)} but the budget "
                f"was refreshed at {budget[dim]} — rerun with --update"
            )

    def gate(name: str, got, limit) -> None:
        if got is None:
            breaches.append(f"{name}: missing from measurement")
        elif float(got) > float(limit) * tol:
            breaches.append(
                f"{name}: {float(got):.1f} ms > budget "
                f"{float(limit):.1f} ms x{tol}"
            )

    gate("step_ms", measured.get("step_ms"), budget["step_ms"])
    for plane, limit in budget.get("plane_ms", {}).items():
        gate(
            f"plane_ms.{plane}",
            measured.get("plane_ms", {}).get(plane),
            limit,
        )
    return not breaches, breaches
