"""Record real host-agent write traffic and replay it in the kernel.

The bridge across the dispatch seam (SURVEY §7 step 7): the reference's
agent pushes every local write into `tx_bcast` (BroadcastInput::AddBroadcast,
corro-types/src/agent.rs:64-69); here each agent's committed writes are
recorded as (time, actor, version) events via `Agent.on_local_write`, and
`replay` re-executes the same write workload inside the whole-cluster
simulator — the scripted `Schedule` becomes a faithful transcript of real
traffic, so kernel visibility/convergence numbers can be read for workloads
that actually happened.

Round mapping: one simulator round is `round_ms` of recorded wall time (the
broadcast flush tick, 500 ms in the reference). Every recorded actor becomes
one writer stream; extra silent observer nodes can be added to study how the
same workload would propagate in a larger cluster.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

import numpy as np

from corrosion_tpu.core.hlc import ts_physical_ms


@dataclass
class Trace:
    """Ordered (t_ms, actor_id, version) write events."""

    events: list[tuple[int, str, int]] = field(default_factory=list)

    def record(self, agent) -> None:
        """Attach to a live Agent: every committed local write appends an
        event (hook installed on Agent.on_local_write)."""

        def hook(actor_id: str, version: int, ts) -> None:
            self.events.append((ts_physical_ms(ts), actor_id, version))

        agent.on_local_write = hook

    def merge(self, other: "Trace") -> "Trace":
        out = Trace(events=sorted(self.events + other.events))
        return out

    @property
    def actors(self) -> list[str]:
        return sorted({a for _, a, _ in self.events})

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            for t, a, v in sorted(self.events):
                f.write(json.dumps([t, a, v]) + "\n")

    @classmethod
    def load(cls, path: str) -> "Trace":
        events = []
        with open(path) as f:
            for line in f:
                t, a, v = json.loads(line)
                events.append((int(t), a, int(v)))
        return cls(events=sorted(events))


def schedule_from_trace(
    trace: Trace, round_ms: float = 500.0, drain_rounds: int = 40,
    samples: int = 256,
):
    """Bucket recorded writes into simulator rounds.

    Returns (actor_ids, Schedule): actor i of the sorted actor list becomes
    writer stream i; writes[r, i] counts the versions actor i committed in
    round r's wall-time window. Versions must be each actor's contiguous
    1..n sequence (they are — the agent allocates them that way); the
    count-per-bucket encoding preserves exactly that order.
    """
    from corrosion_tpu.sim.engine import Schedule

    if not trace.events:
        raise ValueError("empty trace")
    events = sorted(trace.events)
    actors = trace.actors
    a_idx = {a: i for i, a in enumerate(actors)}
    # Sanity: contiguous per-actor version sequences.
    seen: dict[str, int] = {}
    for _, a, v in events:
        expect = seen.get(a, 0) + 1
        if v != expect:
            raise ValueError(
                f"trace gap: actor {a[:8]} version {v}, expected {expect}"
            )
        seen[a] = v
    t0 = events[0][0]
    rounds = int((events[-1][0] - t0) // round_ms) + 1
    writes = np.zeros((rounds + drain_rounds, len(actors)), np.uint32)
    for t, a, _v in events:
        r = int((t - t0) // round_ms)
        writes[r, a_idx[a]] += 1
    return actors, Schedule(writes=writes).make_samples(samples)


def replay(
    trace: Trace, round_ms: float = 500.0, observers: int = 0,
    drain_rounds: int = 40, seed: int = 0, **gossip_kw,
):
    """Re-run a recorded workload in the kernel cluster.

    The recorded actors become writer nodes 0..W-1; ``observers`` adds
    silent nodes that only receive. Returns (actors, final, curves, lat).
    """
    from corrosion_tpu.models.baselines import _cfg
    from corrosion_tpu.sim import simulate, visibility_latencies

    actors, sched = schedule_from_trace(
        trace, round_ms=round_ms, drain_rounds=drain_rounds
    )
    w = len(actors)
    n = w + observers
    max_writes = int(sched.writes.max())
    cfg, topo = _cfg(
        n,
        writers=list(range(w)),
        sync_interval=4,
        n_cells=256,
        max_writes_per_round=max(4, max_writes),
        **gossip_kw,
    )
    final, curves = simulate(cfg, topo, sched, seed=seed)
    lat = visibility_latencies(final, sched, cfg)
    return actors, final, curves, lat
