"""Record real host-agent write traffic and replay it in the kernel.

The bridge across the dispatch seam (SURVEY §7 step 7): the reference's
agent pushes every local write into `tx_bcast` (BroadcastInput::AddBroadcast,
corro-types/src/agent.rs:64-69); here each agent's committed writes are
recorded as (time, actor, version) events via `Agent.on_local_write`, and
`replay` re-executes the same write workload inside the whole-cluster
simulator — the scripted `Schedule` becomes a faithful transcript of real
traffic, so kernel visibility/convergence numbers can be read for workloads
that actually happened.

Round mapping: one simulator round is `round_ms` of recorded wall time (the
broadcast flush tick, 500 ms in the reference). Every recorded actor becomes
one writer stream; extra silent observer nodes can be added to study how the
same workload would propagate in a larger cluster.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

import numpy as np

from corrosion_tpu.core.hlc import ts_physical_ms


@dataclass
class Trace:
    """Ordered (t_ms, actor_id, version) write events."""

    events: list[tuple[int, str, int]] = field(default_factory=list)

    def record(self, agent) -> None:
        """Attach to a live Agent: every committed local write appends an
        event (hook installed on Agent.on_local_write).

        The hook CHAINS with any previously installed one — a second
        recorder (or a user's own hook) must not silently disable the
        first, so the new hook calls the previous hook before appending.
        Detach with :meth:`unrecord`.
        """
        prev = getattr(agent, "on_local_write", None)

        def hook(actor_id: str, version: int, ts) -> None:
            if prev is not None:
                prev(actor_id, version, ts)
            self.events.append((ts_physical_ms(ts), actor_id, version))

        hook._trace_prev = prev  # unrecord support
        hook._trace_owner = self
        agent.on_local_write = hook

    def unrecord(self, agent) -> bool:
        """Detach this trace's hook from ``agent`` if it is the most
        recently installed one, restoring the previous hook. Returns
        False (and leaves the chain alone) when another hook was
        installed on top — unwinding out of order would drop it."""
        hook = getattr(agent, "on_local_write", None)
        if getattr(hook, "_trace_owner", None) is not self:
            return False
        agent.on_local_write = hook._trace_prev
        return True

    def merge(self, other: "Trace") -> "Trace":
        out = Trace(events=sorted(self.events + other.events))
        return out

    @property
    def actors(self) -> list[str]:
        return sorted({a for _, a, _ in self.events})

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            for t, a, v in sorted(self.events):
                f.write(json.dumps([t, a, v]) + "\n")

    @classmethod
    def load(cls, path: str) -> "Trace":
        events = []
        with open(path) as f:
            for line in f:
                t, a, v = json.loads(line)
                events.append((int(t), a, int(v)))
        return cls(events=sorted(events))


def schedule_from_trace(
    trace: Trace, round_ms: float = 500.0, drain_rounds: int = 40,
    samples: int = 256,
):
    """Bucket recorded writes into simulator rounds.

    Returns (actor_ids, Schedule): actor i of the sorted actor list becomes
    writer stream i; writes[r, i] counts the versions actor i committed in
    round r's wall-time window. Versions must be each actor's contiguous
    1..n sequence (they are — the agent allocates them that way); the
    count-per-bucket encoding preserves exactly that order.

    Robust to degenerate inputs: a zero-duration trace (every event in one
    ``round_ms`` window) buckets into a single write round, and sub-ms
    ``round_ms`` values bucket with the same float arithmetic used to size
    the array — the round count is derived from the max bucket index, so a
    boundary event can never index past the array.
    """
    from corrosion_tpu.sim.engine import Schedule

    if not trace.events:
        raise ValueError("empty trace")
    if not round_ms > 0.0:
        raise ValueError(f"round_ms must be positive, got {round_ms}")
    if drain_rounds < 0:
        raise ValueError(f"drain_rounds must be >= 0, got {drain_rounds}")
    events = sorted(trace.events)
    actors = trace.actors
    a_idx = {a: i for i, a in enumerate(actors)}
    # Sanity: contiguous per-actor version sequences — from the FIRST
    # recorded version, not necessarily 1 (a recorder attached mid-life
    # of an agent starts at whatever version the agent is up to).
    seen: dict[str, int] = {}
    for _, a, v in events:
        if a in seen and v != seen[a] + 1:
            raise ValueError(
                f"trace gap: actor {a[:8]} version {v}, expected "
                f"{seen[a] + 1}"
            )
        seen[a] = v
    t0 = events[0][0]
    # Bucket every event FIRST, then size the array from the max bucket:
    # deriving the round count independently (duration // round_ms) can
    # disagree with per-event float floor-division at the last boundary
    # for fractional round_ms, and a zero-duration trace must still give
    # one write round.
    buckets = [int((t - t0) // round_ms) for t, _a, _v in events]
    rounds = max(buckets) + 1
    writes = np.zeros((rounds + drain_rounds, len(actors)), np.uint32)
    for (_t, a, _v), r in zip(events, buckets):
        writes[r, a_idx[a]] += 1
    return actors, Schedule(writes=writes).make_samples(samples)


def replay(
    trace: Trace, round_ms: float = 500.0, observers: int = 0,
    drain_rounds: int = 40, seed: int = 0, **gossip_kw,
):
    """Re-run a recorded workload in the kernel cluster.

    The recorded actors become writer nodes 0..W-1; ``observers`` adds
    silent nodes that only receive. Returns (actors, final, curves, lat).
    """
    from corrosion_tpu.models.baselines import _cfg
    from corrosion_tpu.sim import simulate, visibility_latencies

    actors, sched = schedule_from_trace(
        trace, round_ms=round_ms, drain_rounds=drain_rounds
    )
    w = len(actors)
    n = w + observers
    max_writes = int(sched.writes.max())
    cfg, topo = _cfg(
        n,
        writers=list(range(w)),
        sync_interval=4,
        n_cells=256,
        max_writes_per_round=max(4, max_writes),
        **gossip_kw,
    )
    final, curves = simulate(cfg, topo, sched, seed=seed)
    lat = visibility_latencies(final, sched, cfg)
    return actors, final, curves, lat
