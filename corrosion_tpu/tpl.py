"""Template engine — the corro-tpl analogue.

The reference renders Rhai-scripted file templates with `sql("...")` →
typed rows, `.to_json()` / `.to_csv()`, `hostname()`, atomic tmp+rename
writes, and re-renders whenever a subscription to the template's queries
changes (corro-tpl/src/lib.rs:41-613; watcher corrosion/src/command/tpl.rs).

Here templates are Python-scripted (the idiomatic stand-in for Rhai):
``<% statements %>`` blocks run, ``<%= expression %>`` interpolates, and the
script namespace exposes ``sql``, ``hostname``, ``to_json``, ``to_csv``.
Example:

    # peers.conf.tpl
    <% for row in sql("SELECT id, text FROM tests") { emitted per row } %>
    <%= sql("SELECT count(*) FROM tests").rows[0][0] %> entries

Watch mode subscribes to every query the render used and re-renders on any
change event, writing atomically.
"""

from __future__ import annotations

import asyncio
import csv
import io
import json
import os
import re
import socket

from corrosion_tpu.agent.config import Config, parse_addr
from corrosion_tpu.client import CorrosionApiClient

_TAG = re.compile(r"<%(=?)(.*?)%>", re.S)


class QueryResponse:
    """Rows of one sql() call (QueryResponse, corro-tpl/src/lib.rs:41-248)."""

    def __init__(self, columns: list[str], rows: list[list]):
        self.columns = columns
        self.rows = rows

    def __iter__(self):
        return iter(self.rows)

    def __len__(self):
        return len(self.rows)

    def to_json(self, pretty: bool = False) -> str:
        objs = [dict(zip(self.columns, r)) for r in self.rows]
        return json.dumps(objs, indent=2 if pretty else None)

    def to_csv(self, header: bool = True) -> str:
        out = io.StringIO()
        w = csv.writer(out)
        if header:
            w.writerow(self.columns)
        w.writerows(self.rows)
        return out.getvalue()


def compile_template(text: str):
    """Compile template text into a python function body. Text segments
    emit verbatim; <% %> runs; <%= %> emits the expression."""
    src = ["def __render__(emit, sql, hostname, env):"]
    indent = 1

    def add(line: str):
        src.append("    " * indent + line)

    pos = 0
    for m in _TAG.finditer(text):
        if m.start() > pos:
            add(f"emit({text[pos:m.start()]!r})")
        is_expr, body = m.group(1) == "=", m.group(2).strip()
        if is_expr:
            add(f"emit(str({body}))")
        else:
            for line in body.splitlines():
                stripped = line.strip()
                if not stripped:
                    continue
                if stripped == "end":
                    indent = max(1, indent - 1)
                    continue
                add(stripped)
                if stripped.endswith(":"):
                    indent += 1
        pos = m.end()
    if pos < len(text):
        add(f"emit({text[pos:]!r})")
    ns: dict = {}
    exec("\n".join(src), ns)  # noqa: S102 — templates are operator-authored
    return ns["__render__"]


class _Null:
    """Absorbing placeholder for the query-recording pass."""

    def __getattr__(self, _):
        return self

    def __getitem__(self, _):
        return self

    def __call__(self, *a, **k):
        return self

    def __iter__(self):
        return iter(())

    def __len__(self):
        return 0

    def __str__(self):
        return ""


class _NullResponse(QueryResponse):
    def __init__(self):
        super().__init__([], [])
        self.rows = _Null()
        self.columns = _Null()


class TemplateState:
    """One template file: render + the queries it used (TemplateState,
    corro-tpl lib.rs:361)."""

    def __init__(self, template_path: str, out_path: str, client: CorrosionApiClient):
        self.template_path = template_path
        self.out_path = out_path
        self.client = client
        self.queries: list[str] = []

    async def render_once(self) -> str:
        with open(self.template_path) as f:
            text = f.read()
        fn = compile_template(text)
        chunks: list[str] = []
        self.queries = []

        pending: list[tuple[str, QueryResponse]] = []

        async def fetch(q: str) -> QueryResponse:
            cols, rows = await self.client.query(q)
            return QueryResponse(cols, rows)

        # sql() must be synchronous inside the template; pre-resolve by
        # running the template twice: first pass records queries with empty
        # results, second pass injects fetched data.
        recorded: list[str] = []

        def sql_record(q: str) -> QueryResponse:
            recorded.append(q)
            return _NullResponse()

        try:
            fn(lambda s: None, sql_record, socket.gethostname, {})
        except Exception:
            # The recording pass runs on placeholder data; templates that
            # compute on real rows may fail here — queries recorded so far
            # are what matters.
            pass
        results = {}
        for q in recorded:
            results[q] = await fetch(q)
        self.queries = list(dict.fromkeys(recorded))

        def sql_real(q: str) -> QueryResponse:
            # Explicit membership test: a zero-row QueryResponse is falsy
            # but must keep its real column names.
            return results[q] if q in results else QueryResponse([], [])

        fn(chunks.append, sql_real, socket.gethostname, {})
        return "".join(chunks)

    async def write(self) -> None:
        out = await self.render_once()
        tmp = self.out_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(out)
        os.replace(tmp, self.out_path)  # atomic swap (corro-tpl writes)


async def run_templates(specs: list[str], cfg: Config, watch: bool = False) -> None:
    host, port = parse_addr(cfg.api.addr)
    client = CorrosionApiClient(host, port)
    states = []
    for spec in specs:
        tpl, _, out = spec.partition(":")
        states.append(TemplateState(tpl, out or tpl.removesuffix(".tpl"), client))
    for st in states:
        await st.write()
    if not watch:
        return
    # Re-render on subscription changes to any used query
    # (corrosion/src/command/tpl.rs:29+).
    async def watch_one(st: TemplateState):
        subs = []
        for q in st.queries:
            subs.append(await client.subscribe(q, skip_rows=True))

        async def pump(sub):
            async for ev in sub:
                if "change" in ev:
                    await st.write()

        await asyncio.gather(*(pump(s) for s in subs))

    await asyncio.gather(*(watch_one(st) for st in states))
