"""Template engine — the corro-tpl analogue.

The reference renders Rhai-scripted file templates with `sql("...")` →
typed rows, `.to_json()` / `.to_csv()`, `hostname()`, atomic tmp+rename
writes, and re-renders whenever a subscription to the template's queries
changes (corro-tpl/src/lib.rs:41-613; watcher corrosion/src/command/tpl.rs).

Here templates are Python-scripted (the idiomatic stand-in for Rhai):
``<% statements %>`` blocks run, ``<%= expression %>`` interpolates, and the
script namespace exposes ``sql``, ``hostname``, ``to_json``, ``to_csv``.
Example:

    # peers.conf.tpl
    <% for row in sql("SELECT id, text FROM tests") { emitted per row } %>
    <%= sql("SELECT count(*) FROM tests").rows[0][0] %> entries

Watch mode subscribes to every query the render used and re-renders on any
change event, writing atomically.
"""

from __future__ import annotations

import asyncio
import csv
import io
import json
import logging
import os
import re
import socket

from corrosion_tpu.agent.config import Config, parse_addr
from corrosion_tpu.client import CorrosionApiClient

_TAG = re.compile(r"<%(=?)(.*?)%>", re.S)


class QueryResponse:
    """Rows of one sql() call (QueryResponse, corro-tpl/src/lib.rs:41-248)."""

    def __init__(self, columns: list[str], rows: list[list]):
        self.columns = columns
        self.rows = rows

    def __iter__(self):
        return iter(self.rows)

    def __len__(self):
        return len(self.rows)

    def to_json(self, pretty: bool = False) -> str:
        objs = [dict(zip(self.columns, r)) for r in self.rows]
        return json.dumps(objs, indent=2 if pretty else None)

    def to_csv(self, header: bool = True) -> str:
        out = io.StringIO()
        w = csv.writer(out)
        if header:
            w.writerow(self.columns)
        w.writerows(self.rows)
        return out.getvalue()


def compile_template(text: str):
    """Compile template text into a python function body. Text segments
    emit verbatim; <% %> runs; <%= %> emits the expression."""
    src = ["def __render__(emit, sql, hostname, env):"]
    indent = 1

    def add(line: str):
        src.append("    " * indent + line)

    pos = 0
    for m in _TAG.finditer(text):
        if m.start() > pos:
            add(f"emit({text[pos:m.start()]!r})")
        is_expr, body = m.group(1) == "=", m.group(2).strip()
        if is_expr:
            add(f"emit(str({body}))")
        else:
            for line in body.splitlines():
                stripped = line.strip()
                if not stripped:
                    continue
                if stripped == "end":
                    indent = max(1, indent - 1)
                    continue
                add(stripped)
                if stripped.endswith(":"):
                    indent += 1
        pos = m.end()
    if pos < len(text):
        add(f"emit({text[pos:]!r})")
    ns: dict = {}
    exec("\n".join(src), ns)  # noqa: S102 — templates are operator-authored
    return ns["__render__"]


class TemplateState:
    """One template file: render + the queries it used (TemplateState,
    corro-tpl lib.rs:361)."""

    def __init__(self, template_path: str, out_path: str, client: CorrosionApiClient):
        self.template_path = template_path
        self.out_path = out_path
        self.client = client
        self.queries: list[str] = []
        self._watch_pumps: dict | None = None  # set by watch mode

    async def render_once(self) -> str:
        """Single-pass direct execution, like Rhai's inline sql()
        (corro-tpl/src/lib.rs:447-613): the template body runs ONCE on a
        worker thread, and every sql() call bridges synchronously back to
        the event loop for a live fetch — so a data-dependent nested query
        (sql() inside a loop over another query's rows) sees real rows.
        The queries actually used this render are recorded for watch mode,
        including ones discovered mid-render."""
        with open(self.template_path) as f:
            text = f.read()
        fn = compile_template(text)
        chunks: list[str] = []
        used: list[str] = []
        loop = asyncio.get_running_loop()

        async def fetch(q: str) -> QueryResponse:
            cols, rows = await self.client.query(q)
            return QueryResponse(cols, rows)

        def sql_sync(q: str) -> QueryResponse:
            used.append(q)
            return asyncio.run_coroutine_threadsafe(fetch(q), loop).result(
                timeout=60.0
            )

        await asyncio.to_thread(
            fn, chunks.append, sql_sync, socket.gethostname, {}
        )
        self.queries = list(dict.fromkeys(used))
        return "".join(chunks)

    async def write(self) -> None:
        out = await self.render_once()
        tmp = self.out_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(out)
        os.replace(tmp, self.out_path)  # atomic swap (corro-tpl writes)


async def run_templates(specs: list[str], cfg: Config, watch: bool = False) -> None:
    host, port = parse_addr(cfg.api.addr)
    client = CorrosionApiClient(host, port)
    states = []
    for spec in specs:
        tpl, _, out = spec.partition(":")
        states.append(TemplateState(tpl, out or tpl.removesuffix(".tpl"), client))
    for st in states:
        await st.write()
    if not watch:
        return
    # Re-render on subscription changes to any used query
    # (corrosion/src/command/tpl.rs:29+). Data-dependent templates can
    # discover NEW queries on a re-render (a row appearing makes the loop
    # body fetch for it) — after every render the subscription set is
    # reconciled so late-discovered queries get watched too.
    async def watch_one(st: TemplateState):
        pumps: dict[str, asyncio.Task] = {}
        st._watch_pumps = pumps  # observable for tests/diagnostics
        log = logging.getLogger(__name__)

        async def watch_query(q: str):
            # Subscribe INSIDE the task: reconcile assigns pumps[q]
            # synchronously before any await, so two concurrent renders
            # can never double-subscribe one query.
            sub = await client.subscribe(q, skip_rows=True)
            async for ev in sub:
                if "change" in ev:
                    await st.write()
                    reconcile()

        def reconcile() -> None:
            """Match the pump set to the queries the LAST render used:
            late-discovered queries get watched, queries that dropped out
            (a deleted row's per-row fetch) get cancelled — the set tracks
            the template, it never just grows."""
            want = set(st.queries)
            for q in list(pumps):
                if q not in want:
                    pumps.pop(q).cancel()
            for q in want:
                if q not in pumps:
                    pumps[q] = asyncio.create_task(watch_query(q))

        reconcile()
        while pumps:
            done, _ = await asyncio.wait(
                set(pumps.values()), return_when=asyncio.FIRST_COMPLETED
            )
            for q, t in list(pumps.items()):
                if t in done:
                    del pumps[q]
                    # A dead watch means that query's changes no longer
                    # re-render — surface it (exception retrieval also
                    # silences asyncio's destroyed-task warning).
                    if not t.cancelled():
                        log.warning(
                            "template watch for %r ended; resubscribing",
                            q, exc_info=t.exception(),
                        )
            # Still-wanted queries whose watch died get resubscribed after
            # a re-render (which also catches anything missed while the
            # watch was down) — one transient stream failure must not end
            # watch mode.
            if set(st.queries) - set(pumps):
                await asyncio.sleep(2.0)
                try:
                    await st.write()
                except Exception:
                    log.debug(
                        "template re-render failed; retrying",
                        exc_info=True,
                    )
                reconcile()

    await asyncio.gather(*(watch_one(st) for st in states))
