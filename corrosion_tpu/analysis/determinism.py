"""Determinism taint lints (CT060-CT062).

Three replay contracts currently hold by convention only: traced kernel
code must not bake per-process values into the graph, the netem/fault
schedule planes promise *pure-hash* impairment (docs/CHAOS.md — exact
replay from seed+coordinates), and every committed ``corro-*/N``
artifact feeds a baseline diff gate that is meaningless unless the
bytes are deterministic. These rules make the conventions mechanical:

* CT060 — nondeterministic source (wall clock, ``random``, ``uuid``,
  ``os.urandom``, ``secrets``, builtin ``hash``, unseeded
  ``np.random.default_rng()``) or set-order iteration inside a *traced*
  kernel function. The value is frozen at trace time and differs per
  process/run, so retraces and replays silently disagree.
* CT061 — the same sources anywhere in a declared deterministic-schedule
  module: ``agent/netem.py`` and ``sim/faults.py`` by path, or any
  fixture carrying ``# corro-lint: deterministic-module``. Injected
  generators are fine (a parameter named ``rng`` is the caller's
  problem); *creating* entropy locally is not.
* CT062 — the same sources inside a function that also contains a
  ``corro-<name>/<N>`` format-tag literal, i.e. an artifact emit site.

Set-order iteration means a ``for`` loop directly over a set literal,
set/frozenset() call, or set comprehension — string hashes vary with
PYTHONHASHSEED, so iteration order varies per process. Wrapping in
``sorted()`` is the fix and passes.
"""

from __future__ import annotations

import ast
import re

from corrosion_tpu.analysis.concurrency import _walk_no_defs
from corrosion_tpu.analysis.findings import Finding
from corrosion_tpu.analysis.source import SourceModule, dotted_name

DETERMINISTIC_MARKER = re.compile(
    r"(?m)^\s*#\s*corro-lint:\s*deterministic-module\s*$"
)
# Modules whose outputs are contractually pure functions of
# seed+coordinates (docs/CHAOS.md "Determinism contracts").
_SCHEDULE_FILES = (("agent", "netem.py"), ("sim", "faults.py"))

ARTIFACT_RE = re.compile(r"^corro-[a-z0-9-]+/\d+$")

# dotted name (exact or dotted-prefix "x.") -> why it is nondeterministic
_NONDET = {
    "time.time": "wall clock",
    "time.time_ns": "wall clock",
    "datetime.now": "wall clock",
    "datetime.utcnow": "wall clock",
    "datetime.datetime.now": "wall clock",
    "datetime.datetime.utcnow": "wall clock",
    "random.": "process-seeded global RNG",
    "np.random.": "process-seeded global RNG",
    "numpy.random.": "process-seeded global RNG",
    "os.urandom": "kernel entropy",
    "secrets.": "kernel entropy",
    "uuid.uuid1": "host+clock-derived id",
    "uuid.uuid4": "kernel entropy",
    "hash": "PYTHONHASHSEED-dependent for str/bytes",
}
# Exceptions: explicitly seeded constructions are deterministic.
_SEEDED_OK = ("default_rng", "Generator", "RandomState", "seed", "PRNGKey")


def is_schedule_module(mod: SourceModule) -> bool:
    parts = mod.path.replace("\\", "/").split("/")
    for pkg, name in _SCHEDULE_FILES:
        if parts[-1] == name and pkg in parts[:-1]:
            return True
    return bool(DETERMINISTIC_MARKER.search(mod.text))


def _nondet_reason(call: ast.Call) -> str | None:
    fname = dotted_name(call.func)
    if not fname:
        return None
    last = fname.split(".")[-1]
    if last in _SEEDED_OK and (call.args or call.keywords):
        return None  # seeded/keyed: deterministic by construction
    for prefix, why in _NONDET.items():
        if fname == prefix or (prefix.endswith(".") and
                               fname.startswith(prefix)):
            if last in _SEEDED_OK and not (call.args or call.keywords):
                return f"{why} (unseeded `{fname}()`)"
            return why
    return None


def _set_iteration(node: ast.For | ast.AsyncFor) -> bool:
    it = node.iter
    if isinstance(it, (ast.Set, ast.SetComp)):
        return True
    if isinstance(it, ast.Call):
        return dotted_name(it.func) in ("set", "frozenset")
    return False


def _scan_scope(fn: ast.AST) -> list[tuple[int, int, str]]:
    """(line, col, why) nondeterminism events lexically in ``fn``,
    not descending into nested defs (they are scanned as their own
    scopes)."""
    events: list[tuple[int, int, str]] = []
    for node in _walk_no_defs(fn):
        if isinstance(node, ast.Call):
            why = _nondet_reason(node)
            if why:
                events.append((
                    node.lineno, node.col_offset,
                    f"`{dotted_name(node.func)}`: {why}",
                ))
        elif isinstance(node, (ast.For, ast.AsyncFor)) and \
                _set_iteration(node):
            events.append((
                node.lineno, node.col_offset,
                "iteration over a set: order varies with PYTHONHASHSEED "
                "(wrap in sorted())",
            ))
    return events


def _artifact_tags(fn: ast.AST) -> list[str]:
    tags = []
    for node in _walk_no_defs(fn):
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and ARTIFACT_RE.match(node.value):
            tags.append(node.value)
    return tags


def check_determinism(mod: SourceModule) -> list[Finding]:
    findings: list[Finding] = []
    schedule = is_schedule_module(mod)

    for info in mod.functions:
        events = None
        if info.traced and mod.is_kernel:
            events = events if events is not None else _scan_scope(info.node)
            for line, col, what in events:
                findings.append(Finding(
                    rule="CT060", path=mod.path, line=line, col=col,
                    message=f"{what} in traced `{info.qualname}` — baked "
                    "at trace time, differs per process/run",
                ))
        if schedule:
            events = events if events is not None else _scan_scope(info.node)
            for line, col, what in events:
                findings.append(Finding(
                    rule="CT061", path=mod.path, line=line, col=col,
                    message=f"{what} in deterministic-schedule module — "
                    "schedules must be pure functions of "
                    "seed+coordinates (docs/CHAOS.md)",
                ))
        tags = _artifact_tags(info.node)
        if tags:
            events = events if events is not None else _scan_scope(info.node)
            for line, col, what in events:
                findings.append(Finding(
                    rule="CT062", path=mod.path, line=line, col=col,
                    message=f"{what} in `{info.qualname}`, which emits "
                    f"`{tags[0]}` — committed artifacts must be "
                    "byte-deterministic for their diff gates to hold",
                ))

    # Module-level statements of a schedule module are part of the
    # contract too (import-time entropy is still entropy).
    if schedule:
        mod_level = ast.Module(body=[
            n for n in mod.tree.body
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef))
        ], type_ignores=[])
        for line, col, what in _scan_scope(mod_level):
            findings.append(Finding(
                rule="CT061", path=mod.path, line=line, col=col,
                message=f"{what} at module scope of a "
                "deterministic-schedule module",
            ))
    return findings
