"""Runtime sanitizer: strict-dtype + debug-nans + retrace tripwire.

The static lints can't see promotions synthesized inside jnp internals
or a retrace caused by a host value leaking into a traced shape. This
module runs a *tiny* instance of each engine — two same-shape device
executions, so any shape/const leak forces a second compile — under

- ``jax.numpy_dtype_promotion('strict')``: any implicit promotion
  between two strongly-typed arrays raises (CT031). On TPU an
  unintended u32->i64/f32 widening doubles a tensor's HBM traffic.
- ``jax.debug_nans(True)``: a NaN produced anywhere in the round graph
  raises at the producing primitive (CT032).
- a retrace tripwire: after the run, every jitted function in the
  engine's module must hold at most ONE compile-cache entry (CT030).
  Chunked runs execute the same scanned round repeatedly; a second
  entry means something non-hashable-stable (a host float, a fresh
  tuple of numpy scalars, a closure identity) is being baked into the
  trace — the silent 100x slowdown class.

Imports jax and the engines lazily: `corrosion lint` without
``--sanitize`` never pays for them.
"""

from __future__ import annotations

from corrosion_tpu.analysis.findings import Finding

ENGINES = ("dense", "sparse", "chunk", "mixed")


def _run_dense():
    from corrosion_tpu import models
    from corrosion_tpu.sim import engine

    cfg, topo, sched = models.merge_10k(n=32, rounds=8, samples=8)
    engine.simulate(cfg, topo, sched, seed=0, max_chunk=4)
    return engine


def _run_sparse():
    from corrosion_tpu import models
    from corrosion_tpu.sim import sparse_engine

    cfg, topo, sched = models.anywrite_sparse(
        n=96, w_hot=16, n_regions=4, rounds=16, cohort=8, epoch_rounds=8,
        k_dev=8, samples=16,
    )
    sparse_engine.simulate_sparse(cfg, topo, sched, seed=0)
    return sparse_engine


def _run_chunk():
    from corrosion_tpu.ops.chunks import ChunkConfig
    from corrosion_tpu.sim import chunk_engine

    cfg = ChunkConfig(
        n_nodes=16, n_streams=2, chunk_len=64, fanout=3, sync_interval=4,
        gap_requests=4,
    )
    chunk_engine.simulate_chunks(
        cfg, [0, 5], [511, 255], rounds=8, seed=1, max_chunk=4
    )
    return chunk_engine


def _run_mixed():
    from corrosion_tpu.models.baselines import mixed_storm
    from corrosion_tpu.sim import mixed_engine

    cfg, ccfg, topo, sched, spec = mixed_storm(
        n=64, streams=2, last_seq=255, rounds=8, samples=8, n_cells=0
    )
    mixed_engine.simulate_mixed(cfg, ccfg, topo, sched, spec, seed=0)
    return mixed_engine


_RUNNERS = {
    "dense": _run_dense,
    "sparse": _run_sparse,
    "chunk": _run_chunk,
    "mixed": _run_mixed,
}


def sanitize_engines(
    engines: tuple[str, ...] = ENGINES, strict_dtypes: bool = True,
    check_nans: bool = True,
) -> list[Finding]:
    """Run the tiny-instance sanitizer for ``engines``; returns findings
    (CT030/CT031/CT032), empty when every engine is clean."""
    import contextlib

    import jax

    # The ONE registry of watched jitted functions, shared with the
    # runtime compile ledger and the perf-plane cache pins
    # (obs/ledger.py) — the offline tripwire and the live one can never
    # watch different function sets.
    from corrosion_tpu.obs.ledger import cache_sizes, jitted_functions

    findings: list[Finding] = []
    for name in engines:
        run = _RUNNERS[name]
        jax.clear_caches()
        ctx = contextlib.ExitStack()
        if strict_dtypes:
            ctx.enter_context(jax.numpy_dtype_promotion("strict"))
        if check_nans:
            ctx.enter_context(jax.debug_nans(True))
        try:
            with ctx:
                module = run()
        except FloatingPointError as e:
            findings.append(Finding(
                rule="CT032", path=f"<engine:{name}>", line=0,
                message=f"NaN produced in the {name} round graph: {e}",
            ))
            continue
        except Exception as e:
            # TypePromotionError is matched by name: importing it would
            # pull jax._src internals, and the class moved across jax
            # versions. Anything else is a broken tiny-config run, not a
            # promotion finding — label it honestly (CT033) so triage
            # doesn't chase phantom dtype issues.
            rule = (
                "CT031" if type(e).__name__ == "TypePromotionError"
                else "CT033"
            )
            findings.append(Finding(
                rule=rule, path=f"<engine:{name}>", line=0,
                message=f"{name} engine failed under the sanitizer "
                f"({type(e).__name__}): {e}",
            ))
            continue
        sizes = cache_sizes(jitted_functions(module))
        if not any(sizes.values()):
            # A refactor that renames the scan entry points would turn
            # the tripwire into a no-op; that must be loud, not green.
            findings.append(Finding(
                rule="CT030", path=f"<engine:{name}>", line=0,
                message=f"{module.__name__} exposes no compiled jitted "
                "functions after the run — the retrace tripwire is "
                "watching nothing",
            ))
        for fn_name, size in sizes.items():
            if size > 1:
                findings.append(Finding(
                    rule="CT030", path=f"<engine:{name}>", line=0,
                    message=f"{module.__name__}.{fn_name} compiled "
                    f"{size} times across same-shape chunks — a host "
                    "value is leaking into the trace (retrace tripwire)",
                ))
    return findings
