"""Lint orchestration: discover files, run every rule, apply
suppressions, aggregate a LintResult.

Pure stdlib — importable and runnable without jax. The canonical
telemetry keys come from a static extraction of sim/telemetry.py
(schema.extract_canonical); pass ``telemetry_path`` to lint fixture
trees against a different schema source (the tests do). The engine
clone gate (CT05x) resolves ``analysis/SEAM_MAP.json`` against the
package tree by default; fixture trees pass ``seam_map_path`` +
``seam_root``. ``only`` restricts the run to a changed-file subset
(the ``lint --changed`` mode), and suppressions that match no finding
surface as non-gating CT009 stale warnings so the inventory can't rot.
"""

from __future__ import annotations

import os
import subprocess

from corrosion_tpu.analysis import (
    asynclint,
    clonemap,
    concurrency,
    determinism,
    purity,
    schema,
)
from corrosion_tpu.analysis.findings import Finding, LintResult
from corrosion_tpu.analysis.source import SourceModule

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}

# Rules the static runner never produces (runtime sanitizer family):
# their suppressions are consumed by `lint --sanitize`, so a static run
# must not call them stale.
_RUNTIME_RULES_PREFIX = "CT03"


def default_telemetry_path() -> str:
    import corrosion_tpu

    return os.path.join(
        os.path.dirname(corrosion_tpu.__file__), "sim", "telemetry.py"
    )


def default_seam_root() -> str:
    import corrosion_tpu

    return os.path.dirname(os.path.abspath(corrosion_tpu.__file__))


def discover(paths: list[str]) -> list[str]:
    files: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
            continue
        for root, dirs, names in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
            files.extend(
                os.path.join(root, n) for n in sorted(names)
                if n.endswith(".py")
            )
    return files


def changed_files(ref: str, cwd: str | None = None) -> set[str]:
    """Absolute real paths of files changed vs ``ref`` (committed diff
    plus untracked), for ``lint --changed``. Raises RuntimeError when
    git can't answer (not a repo, unknown ref)."""
    top = subprocess.run(
        ["git", "rev-parse", "--show-toplevel"],
        cwd=cwd, capture_output=True, text=True,
    )
    if top.returncode != 0:
        raise RuntimeError(f"not a git repository: {top.stderr.strip()}")
    root = top.stdout.strip()
    out: set[str] = set()
    diff = subprocess.run(
        ["git", "diff", "--name-only", ref, "--"],
        cwd=root, capture_output=True, text=True,
    )
    if diff.returncode != 0:
        raise RuntimeError(
            f"git diff vs {ref!r} failed: {diff.stderr.strip()}"
        )
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard"],
        cwd=root, capture_output=True, text=True,
    )
    for blob in (diff.stdout, untracked.stdout if
                 untracked.returncode == 0 else ""):
        for name in blob.splitlines():
            if name.strip():
                out.add(os.path.realpath(os.path.join(root, name.strip())))
    return out


def lint_paths(
    paths: list[str],
    rules: set[str] | None = None,
    telemetry_path: str | None = None,
    seam_map_path: str | None = None,
    seam_root: str | None = None,
    only: set[str] | None = None,
) -> LintResult:
    """Run every static rule over ``paths`` (files or trees).

    ``rules`` filters to a subset of CT0xx ids; suppressed findings are
    reported separately (they never gate) and CT000 fires on malformed
    suppressions — a suppression without a reason is ignored, loudly.
    ``only`` (absolute real paths) restricts to a changed-file subset.
    """
    result = LintResult()
    tpath = telemetry_path or default_telemetry_path()
    try:
        canonical = schema.extract_canonical(tpath)
    except OSError:
        canonical = {}
    if "ROUND_CURVE_KEYS" not in canonical:
        result.findings.append(Finding(
            rule="CT010", path=tpath, line=1,
            message="static extraction of ROUND_CURVE_KEYS failed — the "
            "schema-parity lint is blind; keep the canonical tuples "
            "statically evaluable",
        ))
    result.canonical_keys = tuple(canonical.get("ROUND_CURVE_KEYS", ()))

    engine_paths: list[str] = []
    for path in discover(paths):
        if only is not None and os.path.realpath(path) not in only:
            continue
        try:
            mod = SourceModule(path)
        except (SyntaxError, UnicodeDecodeError) as e:
            result.findings.append(Finding(
                rule="CT000", path=path,
                line=getattr(e, "lineno", 1) or 1,
                message=f"unparsable source: {e}",
            ))
            result.files += 1
            continue
        result.files += 1
        found: list[Finding] = []
        found.extend(purity.check_purity(mod))
        keys, schema_findings = schema.emitted_keys(mod, canonical)
        found.extend(schema_findings)
        if mod.is_engine:
            name = os.path.splitext(os.path.basename(path))[0]
            result.engines[name] = keys
            engine_paths.append(path)
        found.extend(concurrency.check_concurrency(mod))
        found.extend(asynclint.check_async(mod))
        found.extend(determinism.check_determinism(mod))
        for line, msg in mod.bad_suppressions:
            found.append(Finding(rule="CT000", path=path, line=line,
                                 message=msg))
        matched: set[tuple[int, str]] = set()
        for f in found:
            if rules is not None and f.rule not in rules:
                continue
            sup = mod.suppression_for(f.rule, f.line)
            if sup is not None:
                matched.add((id(sup), f.rule))
                f.suppressed = True
                f.suppress_reason = sup.reason
                result.suppressed.append(f)
            else:
                result.findings.append(f)
        if rules is None or "CT009" in rules:
            for s in mod.suppressions:
                for r in sorted(s.rules):
                    if r.startswith(_RUNTIME_RULES_PREFIX):
                        continue  # consumed by the runtime sanitizer
                    if rules is not None and r not in rules:
                        continue  # rule not active: staleness unknown
                    if (id(s), r) not in matched:
                        result.stale.append(Finding(
                            rule="CT009", path=path, line=s.line,
                            message=f"suppression for {r} no longer "
                            "matches any finding — delete it (reason "
                            f"was: {s.reason!r})",
                        ))

    # Cross-module engine-clone gate: runs when the linted set reaches
    # engine files (so fixture-tree lints stay self-contained unless
    # they pass their own map).
    explicit_map = seam_map_path is not None
    smap_path = seam_map_path or clonemap.default_seam_map_path()
    root = seam_root or default_seam_root()
    in_root = any(
        os.path.realpath(p).startswith(os.path.realpath(root) + os.sep)
        for p in engine_paths
    )
    if engine_paths and (explicit_map or in_root):
        clone_found: list[Finding] = []
        try:
            smap = clonemap.load_seam_map(smap_path)
        except OSError as e:
            clone_found.append(Finding(
                rule="CT051", path=smap_path, line=1,
                message=f"seam map unreadable: {e} — the engine-clone "
                "gate is blind",
            ))
            smap = None
        except ValueError as e:
            clone_found.append(Finding(
                rule="CT051", path=smap_path, line=1, message=str(e),
            ))
            smap = None
        if smap is not None:
            clone_found.extend(clonemap.check_clones(smap, root))
            clone_found.extend(clonemap.check_partial_keys(
                smap, result.engines, result.canonical_keys, smap_path,
            ))
        for f in clone_found:
            if rules is None or f.rule in rules:
                result.findings.append(f)

    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    result.suppressed.sort(key=lambda f: (f.path, f.line, f.rule))
    result.stale.sort(key=lambda f: (f.path, f.line, f.rule))
    return result
