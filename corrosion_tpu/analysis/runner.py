"""Lint orchestration: discover files, run every rule, apply
suppressions, aggregate a LintResult.

Pure stdlib — importable and runnable without jax. The canonical
telemetry keys come from a static extraction of sim/telemetry.py
(schema.extract_canonical); pass ``telemetry_path`` to lint fixture
trees against a different schema source (the tests do).
"""

from __future__ import annotations

import os

from corrosion_tpu.analysis import concurrency, purity, schema
from corrosion_tpu.analysis.findings import Finding, LintResult
from corrosion_tpu.analysis.source import SourceModule

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def default_telemetry_path() -> str:
    import corrosion_tpu

    return os.path.join(
        os.path.dirname(corrosion_tpu.__file__), "sim", "telemetry.py"
    )


def discover(paths: list[str]) -> list[str]:
    files: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
            continue
        for root, dirs, names in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
            files.extend(
                os.path.join(root, n) for n in sorted(names)
                if n.endswith(".py")
            )
    return files


def lint_paths(
    paths: list[str],
    rules: set[str] | None = None,
    telemetry_path: str | None = None,
) -> LintResult:
    """Run every static rule over ``paths`` (files or trees).

    ``rules`` filters to a subset of CT0xx ids; suppressed findings are
    reported separately (they never gate) and CT000 fires on malformed
    suppressions — a suppression without a reason is ignored, loudly.
    """
    result = LintResult()
    tpath = telemetry_path or default_telemetry_path()
    try:
        canonical = schema.extract_canonical(tpath)
    except OSError:
        canonical = {}
    if "ROUND_CURVE_KEYS" not in canonical:
        result.findings.append(Finding(
            rule="CT010", path=tpath, line=1,
            message="static extraction of ROUND_CURVE_KEYS failed — the "
            "schema-parity lint is blind; keep the canonical tuples "
            "statically evaluable",
        ))
    result.canonical_keys = tuple(canonical.get("ROUND_CURVE_KEYS", ()))

    for path in discover(paths):
        try:
            mod = SourceModule(path)
        except (SyntaxError, UnicodeDecodeError) as e:
            result.findings.append(Finding(
                rule="CT000", path=path,
                line=getattr(e, "lineno", 1) or 1,
                message=f"unparsable source: {e}",
            ))
            result.files += 1
            continue
        result.files += 1
        found: list[Finding] = []
        found.extend(purity.check_purity(mod))
        keys, schema_findings = schema.emitted_keys(mod, canonical)
        found.extend(schema_findings)
        if mod.is_engine:
            name = os.path.splitext(os.path.basename(path))[0]
            result.engines[name] = keys
        found.extend(concurrency.check_concurrency(mod))
        for line, msg in mod.bad_suppressions:
            found.append(Finding(rule="CT000", path=path, line=line,
                                 message=msg))
        for f in found:
            if rules is not None and f.rule not in rules:
                continue
            sup = mod.suppression_for(f.rule, f.line)
            if sup is not None:
                f.suppressed = True
                f.suppress_reason = sup.reason
                result.suppressed.append(f)
            else:
                result.findings.append(f)
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    result.suppressed.sort(key=lambda f: (f.path, f.line, f.rule))
    return result
