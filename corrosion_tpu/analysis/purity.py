"""Kernel-purity lints (CT001-CT005) over kernel modules.

Targets the failure modes that silently wreck a scanned round step on
TPU: host round-trips (numpy on traced values, float()/int() coercions)
that serialize the device per call, dtype-less literals whose promotion
drifts downstream widths, and Python control flow on traced values that
either retraces per value or raises at trace time. Scope per rule:

- CT002/CT003 apply module-wide in kernel modules (a dtype-less literal
  is a hazard wherever the array ends up feeding a kernel).
- CT001/CT004 apply inside *traced* functions (jit-decorated, scan/cond
  bodies, nested in one — or presumed, in ``ops/``).
- CT005 applies only to explicitly-traced functions (scan bodies and
  jit-decorated defs), where a parameter is traced by construction;
  jit static_argnames are exempt, as are shape/dtype attribute tests
  and ``is None`` checks (static at trace time).
"""

from __future__ import annotations

import ast

from corrosion_tpu.analysis.findings import Finding
from corrosion_tpu.analysis.source import FunctionInfo, SourceModule, dotted_name

# jnp constructors that take an optional dtype and default to promotion-
# prone widths. zeros_like/asarray/arange are excluded: _like preserves
# dtype, asarray converts an existing array, and arange's int default is
# stable (documented in docs/ANALYSIS.md).
_DTYPE_CTORS = {"array", "zeros", "ones", "full", "empty"}
# positional index where dtype may appear per ctor.
_DTYPE_POS = {"array": 1, "zeros": 1, "ones": 1, "empty": 1, "full": 2}

_COERCIONS = {"float", "int", "bool"}
_COERCION_METHODS = {"item", "tolist"}

# attribute reads that are static at trace time even on traced values.
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}


def _has_dtype(call: ast.Call, ctor: str) -> bool:
    if any(kw.arg == "dtype" for kw in call.keywords):
        return True
    return len(call.args) > _DTYPE_POS[ctor]


def _is_constant_expr(node: ast.AST) -> bool:
    return all(
        isinstance(
            n,
            (ast.Constant, ast.Tuple, ast.List, ast.UnaryOp, ast.BinOp,
             ast.USub, ast.UAdd, ast.operator, ast.unaryop, ast.Load),
        )
        for n in ast.walk(node)
    )


def _static_only_test(test: ast.AST, fn: FunctionInfo) -> bool:
    """True when a branch test cannot involve a traced value: `is None`
    comparisons, isinstance/len on anything, shape/dtype attribute
    chains, and names in jit static_argnames."""
    static_names: set[str] = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return True
        if isinstance(node, ast.Call) and dotted_name(node.func) in (
            "isinstance", "len", "hasattr", "getattr"
        ):
            return True
        # `x is None` / `x is not None` tests identity of the pytree
        # structure, which is static at trace time — names under such a
        # Compare never witness a traced *value*.
        if isinstance(node, ast.Compare) and any(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
        ):
            static_names |= {
                n.id for n in ast.walk(node) if isinstance(n, ast.Name)
            }
    names = {
        n.id for n in ast.walk(test) if isinstance(n, ast.Name)
    }
    params = _param_names(fn)
    hits = (names - static_names) & params
    return not hits or hits <= fn.static_params


def _param_names(fn: FunctionInfo) -> set[str]:
    a = fn.node.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def check_purity(mod: SourceModule) -> list[Finding]:
    if not mod.is_kernel:
        return []
    out: list[Finding] = []
    # value-position Attribute nodes: `np.random.default_rng` should fire
    # once (outermost), not once per link of the chain.
    inner_attrs = {
        id(n.value) for n in ast.walk(mod.tree)
        if isinstance(n, ast.Attribute)
    }

    def add(rule: str, node: ast.AST, msg: str) -> None:
        out.append(
            Finding(rule=rule, path=mod.path, line=node.lineno,
                    col=node.col_offset, message=msg)
        )

    for node in ast.walk(mod.tree):
        # CT002: function-local numpy import anywhere in a kernel module.
        if isinstance(node, ast.Import):
            fn = mod.enclosing_function(node)
            if fn is not None:
                for alias in node.names:
                    if alias.name == "numpy" or alias.name.startswith(
                        "numpy."
                    ):
                        add(
                            "CT002", node,
                            f"function-local `import {alias.name}` in "
                            f"kernel function {fn.qualname}; hoist to "
                            "module scope or suppress with a reason",
                        )

        # CT003: dtype-less jnp literal constructors, module-wide.
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            parts = fname.split(".")
            if (
                len(parts) == 2
                and parts[0] in ("jnp", "jax_numpy")
                and parts[1] in _DTYPE_CTORS
                and not _has_dtype(node, parts[1])
            ):
                add(
                    "CT003", node,
                    f"`{fname}(...)` without an explicit dtype; default "
                    "promotion drifts downstream widths — state it",
                )

        fn = mod.enclosing_function(node)
        traced = fn is not None and fn.traced
        if not traced:
            continue

        # CT001: numpy usage inside traced code.
        if isinstance(node, ast.Attribute) and id(node) not in inner_attrs:
            root = node
            while isinstance(root.value, ast.Attribute):
                root = root.value
            if isinstance(root.value, ast.Name) and root.value.id in (
                "np", "numpy"
            ):
                add(
                    "CT001", node,
                    f"numpy reference `{dotted_name(node)}` inside traced "
                    f"function {fn.qualname} — host-trip hazard",
                )

        # CT004: host coercions of (potentially) traced values.
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if fname in _COERCIONS and node.args:
                arg = node.args[0]
                arg_names = {
                    n.id for n in ast.walk(arg) if isinstance(n, ast.Name)
                }
                static_ok = (
                    _is_constant_expr(arg)
                    or arg_names <= fn.static_params
                    or any(
                        isinstance(a, ast.Attribute)
                        and a.attr in _STATIC_ATTRS
                        for a in ast.walk(arg)
                    )
                )
                if not static_ok:
                    add(
                        "CT004", node,
                        f"`{fname}(...)` coercion inside traced function "
                        f"{fn.qualname} — forces a device sync (or "
                        "TracerConversion error) per call",
                    )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _COERCION_METHODS
            ):
                add(
                    "CT004", node,
                    f"`.{node.func.attr}()` inside traced function "
                    f"{fn.qualname} — forces a device sync per call",
                )

        # CT005: Python branch on a traced parameter (explicit traced
        # functions only — the presumption would false-positive on
        # host-config branches).
        if isinstance(node, (ast.If, ast.While)) and fn.explicit_traced:
            if not _static_only_test(node.test, fn):
                kind = "if" if isinstance(node, ast.If) else "while"
                add(
                    "CT005", node,
                    f"Python `{kind}` on traced value(s) in "
                    f"{fn.qualname} ({fn.traced_why}); use lax.cond/"
                    "lax.select or mark the argument static",
                )
    return out
