"""Engine-clone drift gate (CT050-CT052) over the four sim engines.

ROADMAP item 4 names the 4× engine tax: every per-round plane is
threaded by hand through ``sim/{engine,sparse_engine,chunk_engine,
mixed_engine}.py``, and the copies drift — CT010 only checks telemetry
keys and the parity tests catch drift at runtime, after the fact. This
module makes the clone relationship *declared state*:

``analysis/SEAM_MAP.json`` (format ``corro-seam-map/1``) lists

* ``clones`` — function pairs that are intentional copies, with a
  per-pair ``renames`` table (b-side identifier -> a-side identifier)
  and a ``seams`` list: the hunks where the copies *legitimately*
  differ, stored as normalized source fragments with a name and a why.
* ``partial_keys`` — waivers for canonical round-curve keys that are
  deliberately emitted by fewer than all four engines, with the exact
  engine set and a why.

The analyzer parses each mapped function, strips docstrings, applies
the declared renames, unparses to canonical lines, and diffs the pair.
Every non-equal hunk must exactly match a declared seam, else **CT050**
fires with the stray fragment. A mapped function or file that no longer
exists fires **CT051** (item 4's collapse deletes map entries as proof
of progress — deliberately). A canonical key emitted by some but not
all engines without a matching waiver (or with a stale waiver naming
the wrong engine set) fires **CT052**: a new per-round plane landed in
fewer than four copies.

``refresh_seams`` regenerates the seam lists from the live diff while
preserving the name/why of seams that still match — the committed-map
update flow (``lint --update-seams``), same idiom as the
``COST_BASELINE`` ``--update`` flow.
"""

from __future__ import annotations

import ast
import difflib
import json
import os

from corrosion_tpu.analysis.findings import Finding

SEAM_MAP_FORMAT = "corro-seam-map/1"


def default_seam_map_path() -> str:
    return os.path.join(os.path.dirname(__file__), "SEAM_MAP.json")


def load_seam_map(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("format") != SEAM_MAP_FORMAT:
        raise ValueError(
            f"seam map {path}: format {data.get('format')!r}, "
            f"expected {SEAM_MAP_FORMAT!r}"
        )
    return data


# -- normalization -------------------------------------------------------

class _Renamer(ast.NodeTransformer):
    def __init__(self, renames: dict[str, str]):
        self.renames = renames

    def _r(self, name: str) -> str:
        return self.renames.get(name, name)

    def visit_Name(self, node: ast.Name):
        node.id = self._r(node.id)
        return self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        node.attr = self._r(node.attr)
        return self.generic_visit(node)

    def visit_arg(self, node: ast.arg):
        node.arg = self._r(node.arg)
        return self.generic_visit(node)

    def visit_keyword(self, node: ast.keyword):
        if node.arg is not None:
            node.arg = self._r(node.arg)
        return self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef):
        node.name = self._r(node.name)
        return self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        node.name = self._r(node.name)
        return self.generic_visit(node)


def _strip_docstrings(node: ast.AST) -> None:
    for n in ast.walk(node):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Module)):
            body = getattr(n, "body", [])
            if body and isinstance(body[0], ast.Expr) and isinstance(
                    body[0].value, ast.Constant) and isinstance(
                    body[0].value.value, str):
                n.body = body[1:] or [ast.Pass()]


def resolve_function(tree: ast.Module, qualname: str):
    """Find a (possibly nested) function by dotted qualname, e.g.
    ``_scan_impl.body``. Returns the node or None."""
    scope: ast.AST = tree
    node = None
    for part in qualname.split("."):
        node = None
        for child in ast.walk(scope):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)) and child.name == part:
                node = child
                break
        if node is None:
            return None
        scope = node
    return node


def normalize(fn: ast.AST, renames: dict[str, str] | None = None
              ) -> list[str]:
    """Canonical source lines for one clone body: docstrings stripped,
    declared renames applied, comments/formatting gone via unparse.
    The ``def`` header is kept (renames cover the name delta) so
    signature drift is visible too."""
    import copy

    fn = copy.deepcopy(fn)
    _strip_docstrings(fn)
    if renames:
        fn = _Renamer(dict(renames)).visit(fn)
    ast.fix_missing_locations(fn)
    return ast.unparse(fn).splitlines()


def diff_hunks(a_lines: list[str], b_lines: list[str]
               ) -> list[tuple[list[str], list[str]]]:
    sm = difflib.SequenceMatcher(None, a_lines, b_lines, autojunk=False)
    hunks = []
    for tag, i1, i2, j1, j2 in sm.get_opcodes():
        if tag != "equal":
            hunks.append((a_lines[i1:i2], b_lines[j1:j2]))
    return hunks


# -- the checks ----------------------------------------------------------

def _side(root: str, spec: dict):
    """(path, tree|None, fn|None) for one side of a clone pair."""
    path = os.path.join(root, spec["file"].replace("/", os.sep))
    if not os.path.isfile(path):
        return path, None, None
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    return path, tree, resolve_function(tree, spec["func"])


def check_clones(seam_map: dict, root: str) -> list[Finding]:
    """CT050/CT051 over every declared clone pair. ``root`` is the
    directory the map's relative file paths resolve against (the
    ``corrosion_tpu`` package directory in production)."""
    findings: list[Finding] = []
    for pair in seam_map.get("clones", []):
        name = pair.get("name", "?")
        sides = {}
        missing = False
        for key in ("a", "b"):
            path, tree, fn = _side(root, pair[key])
            sides[key] = (path, fn)
            if fn is None:
                findings.append(Finding(
                    rule="CT051", path=path, line=1,
                    message=(
                        f"clone pair `{name}`: "
                        + (f"function `{pair[key]['func']}` not found"
                           if tree is not None else "file missing")
                        + " — collapse complete? delete the map entry "
                        "deliberately (ROADMAP item 4 workflow in "
                        "docs/ANALYSIS.md)"
                    ),
                ))
                missing = True
        if missing:
            continue
        a_path, a_fn = sides["a"]
        b_path, b_fn = sides["b"]
        a_lines = normalize(a_fn)
        b_lines = normalize(b_fn, pair.get("renames", {}))
        declared = [
            (s.get("a", []), s.get("b", []))
            for s in pair.get("seams", [])
        ]
        for hunk_a, hunk_b in diff_hunks(a_lines, b_lines):
            if (hunk_a, hunk_b) in ((list(da), list(db))
                                    for da, db in declared):
                continue
            frag = (hunk_b or hunk_a)[0].strip()
            findings.append(Finding(
                rule="CT050", path=b_path, line=b_fn.lineno,
                message=f"clone pair `{name}` "
                f"({pair['a']['file']}:{pair['a']['func']} vs "
                f"{pair['b']['file']}:{pair['b']['func']}) diverges "
                f"outside declared seams near `{frag}` "
                f"({len(hunk_a)}a/{len(hunk_b)}b lines) — re-sync the "
                "copies or declare the seam (lint --update-seams, then "
                "fill in the why)",
            ))
    return findings


def check_partial_keys(seam_map: dict, engines: dict[str, list[str]],
                       canonical: tuple[str, ...],
                       map_path: str) -> list[Finding]:
    """CT052: canonical keys emitted by a strict subset of the engines
    must carry a waiver naming that exact subset."""
    findings: list[Finding] = []
    if len(engines) < 4:
        return findings  # partial lint scope: subset judgement unsound
    waivers = seam_map.get("partial_keys", {})
    all_names = sorted(engines)
    for key in canonical:
        emitting = sorted(n for n, keys in engines.items() if key in keys)
        if not emitting or emitting == all_names:
            continue
        waiver = waivers.get(key)
        if waiver is None:
            findings.append(Finding(
                rule="CT052", path=map_path, line=1,
                message=f"round-curve key `{key}` emitted by "
                f"{emitting} but not {sorted(set(all_names) - set(emitting))} "
                "and carries no partial_keys waiver — thread the plane "
                "through all four engines or declare the waiver with a "
                "why",
            ))
        elif sorted(waiver.get("engines", [])) != emitting:
            findings.append(Finding(
                rule="CT052", path=map_path, line=1,
                message=f"stale waiver for `{key}`: declared engines "
                f"{sorted(waiver.get('engines', []))} but measured "
                f"{emitting} — update the waiver",
            ))
    return findings


# -- map maintenance -----------------------------------------------------

def refresh_seams(seam_map: dict, root: str) -> tuple[dict, int]:
    """Regenerate every pair's ``seams`` from the live diff, keeping
    name/why for hunks that still match a declared seam. Returns the
    new map and the count of fresh (TODO-why) seams introduced."""
    out = json.loads(json.dumps(seam_map))  # deep copy
    fresh = 0
    for pair in out.get("clones", []):
        _, _, a_fn = _side(root, pair["a"])
        _, _, b_fn = _side(root, pair["b"])
        if a_fn is None or b_fn is None:
            continue  # CT051 territory; refresh can't help
        a_lines = normalize(a_fn)
        b_lines = normalize(b_fn, pair.get("renames", {}))
        old = {
            (tuple(s.get("a", [])), tuple(s.get("b", []))): s
            for s in pair.get("seams", [])
        }
        seams = []
        for i, (ha, hb) in enumerate(diff_hunks(a_lines, b_lines)):
            prev = old.get((tuple(ha), tuple(hb)))
            if prev is not None:
                seams.append(prev)
            else:
                fresh += 1
                seams.append({
                    "name": f"{pair.get('name', 'pair')}-seam-{i}",
                    "why": "TODO: describe why the copies differ here",
                    "a": ha,
                    "b": hb,
                })
        pair["seams"] = seams
    return out, fresh


def save_seam_map(seam_map: dict, path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(seam_map, f, indent=2, sort_keys=False)
        f.write("\n")
