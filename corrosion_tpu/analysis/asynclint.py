"""Asyncio race & lifecycle lints for the agent plane (CT040-CT043).

The agent serves gossip, sync, the HTTP API, and admin RPC from one
event loop; its state-lifecycle bugs look nothing like the lock bugs
CT020/CT021 catch. Both real host bugs PR 14 found (accepted sockets
surviving death, the partition-heal membership wedge) and PR 8's
listener-queue drop were of this family:

* CT040 — an async method reads ``self.X``, suspends at an ``await``,
  then writes ``self.X`` back without holding a guarding lock. A second
  task interleaves at the await and one update is lost (check-then-act
  across a suspension point). Lock resolution reuses CT020's name
  heuristics; reads/writes under a lock-ish ``with``/``async with`` are
  exempt, as are lock-ish attributes themselves.
* CT041 — fire-and-forget ``create_task``/``ensure_future``: the
  returned task is neither stored, awaited, nor given
  ``add_done_callback``. Its exception vanishes and CPython may GC the
  task mid-run. TaskGroup-style receivers (``tg.create_task``) hold the
  task themselves and are exempt.
* CT042 — blocking call lexically inside ``async def``: the hard set
  (``time.sleep``, subprocess, socket dial/resolve, blocking HTTP,
  ``sqlite3.connect``) fires everywhere; ``open()`` and sync sqlite
  ``execute*`` on conn/cursor-named receivers fire only in agent-plane
  modules (``corrosion_tpu/agent/`` or ``# corro-lint: agent-module``
  fixtures) — one-shot CLI helpers may block, the serving loop may not.
* CT043 — an ``except`` handler in an ``async def`` that catches
  ``asyncio.CancelledError`` (directly, bare, or via ``BaseException``)
  without a ``raise`` in the handler. Exemption: the cancel-and-await
  teardown idiom (a ``.cancel()`` call lexically before the ``try`` in
  the same function) is how you *intentionally* absorb the
  CancelledError you caused.

Findings attribute to the innermost enclosing function so nested async
defs (connection handlers inside ``start``) report once.
"""

from __future__ import annotations

import ast
import re

from corrosion_tpu.analysis.concurrency import _lock_identity, _walk_no_defs
from corrosion_tpu.analysis.findings import Finding
from corrosion_tpu.analysis.source import SourceModule, dotted_name

AGENT_MARKER = re.compile(r"(?m)^\s*#\s*corro-lint:\s*agent-module\s*$")

# Lock-ish attribute names never count as racy state (they ARE the
# guard); mirrors concurrency._LOCKISH.
_LOCKISH_ATTR = re.compile(
    r"(?:^|_)(?:r|w)?(?:lock|mutex|guard|sem|semaphore)s?$", re.IGNORECASE
)

# Hard-blocking dotted prefixes: fire in any async def, any module.
_BLOCKING_ASYNC = {
    "time.sleep": "sleeps the whole event loop (use asyncio.sleep)",
    "subprocess.": "spawns and waits on a child process",
    "os.system": "spawns a shell and waits",
    "os.popen": "spawns a shell",
    "socket.create_connection": "dials TCP synchronously",
    "socket.getaddrinfo": "resolves DNS synchronously",
    "socket.gethostbyname": "resolves DNS synchronously",
    "requests.": "performs a blocking HTTP request",
    "urllib.request.": "performs a blocking HTTP request",
    "sqlite3.connect": "opens a database file synchronously",
}

# Receiver name (last dotted segment) that marks a sync sqlite handle.
_DBISH = re.compile(r"(?:^|_)(?:conn|connection|db|cur|cursor)$",
                    re.IGNORECASE)
_EXEC_METHODS = ("execute", "executemany", "executescript")

_TASK_SPAWNERS = ("create_task", "ensure_future")


def is_agent_module(mod: SourceModule) -> bool:
    parts = mod.path.replace("\\", "/").split("/")
    return "agent" in parts[:-1] or bool(AGENT_MARKER.search(mod.text))


def _self_attr(node: ast.AST) -> str | None:
    """Attribute name when ``node`` is ``self.X`` (one level), else None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _async_functions(mod: SourceModule):
    for info in mod.functions:
        if isinstance(info.node, ast.AsyncFunctionDef):
            yield info


# -- CT040 ---------------------------------------------------------------

def _ct040(mod: SourceModule) -> list[Finding]:
    findings: list[Finding] = []
    for info in _async_functions(mod):
        # Ordered event stream: (line, kind, attr) with kind in
        # {read, write, await}; lock-guarded regions contribute no
        # read/write events (the lock serializes them).
        events: list[tuple[int, str, str]] = []

        def scan(node: ast.AST, guarded: bool):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Lambda)):
                    continue
                now_guarded = guarded
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    if any(_lock_identity(item, None) or
                           _lock_identity(item, "C")
                           for item in child.items):
                        now_guarded = True
                if isinstance(child, (ast.Await, ast.AsyncFor)):
                    events.append((child.lineno, "await", ""))
                attr = _self_attr(child)
                if attr is not None and not _LOCKISH_ATTR.search(attr):
                    if not guarded:
                        kind = ("write" if isinstance(child.ctx,
                                                      (ast.Store, ast.Del))
                                else "read")
                        events.append((child.lineno, kind, attr))
                # self._x[k] = v / del self._x[k]: a write to _x.
                if isinstance(child, ast.Subscript) and isinstance(
                        child.ctx, (ast.Store, ast.Del)):
                    sattr = _self_attr(child.value)
                    if sattr is not None and not guarded \
                            and not _LOCKISH_ATTR.search(sattr):
                        events.append((child.lineno, "write", sattr))
                scan(child, now_guarded)

        scan(info.node, False)
        events.sort(key=lambda e: e[0])
        # For each attr: unguarded touch, then an await, then an
        # unguarded write -> the write clobbers concurrent updates.
        seen_before: dict[str, int] = {}
        awaited_after: dict[str, int] = {}
        reported: set[str] = set()
        for line, kind, attr in events:
            if kind == "await":
                for a in seen_before:
                    awaited_after.setdefault(a, line)
                continue
            if kind == "write" and attr in awaited_after \
                    and attr not in reported:
                reported.add(attr)
                findings.append(Finding(
                    rule="CT040", path=mod.path, line=line,
                    message=f"`self.{attr}` written after the await at "
                    f"line {awaited_after[attr]} that follows its read at "
                    f"line {seen_before[attr]} in `{info.qualname}` — a "
                    "concurrent task can interleave at the await; guard "
                    "the read+write with one lock or capture-and-swap "
                    "before awaiting",
                ))
            seen_before.setdefault(attr, line)
    return findings


# -- CT041 ---------------------------------------------------------------

def _ct041(mod: SourceModule) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(mod.tree):
        call: ast.Call | None = None
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            call = node.value
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            # `_ = create_task(...)` is still fire-and-forget.
            if all(isinstance(t, ast.Name) and t.id == "_"
                   for t in node.targets):
                call = node.value
        if call is None:
            continue
        fname = dotted_name(call.func)
        if fname.split(".")[-1] not in _TASK_SPAWNERS:
            continue
        receiver = fname.rsplit(".", 1)[0] if "." in fname else ""
        if "group" in receiver.lower() or receiver.split(".")[-1] == "tg":
            continue  # TaskGroup holds its children
        findings.append(Finding(
            rule="CT041", path=mod.path, line=node.lineno,
            message=f"`{fname}` result dropped — store the task (and "
            "await or add_done_callback it) so its exception cannot "
            "vanish and the task cannot be garbage-collected mid-run",
        ))
    return findings


# -- CT042 ---------------------------------------------------------------

def _conn_locals(fn: ast.AST) -> set[str]:
    """Local names bound to a sqlite conn/cursor-ish expression inside
    ``fn`` (``c = self.store.conn.cursor()``, ``conn = ...``)."""
    names: set[str] = set()
    for node in _walk_no_defs(fn):
        if not isinstance(node, ast.Assign):
            continue
        src = node.value
        dname = dotted_name(src.func) if isinstance(src, ast.Call) else \
            dotted_name(src)
        last = dname.split(".")[-1] if dname else ""
        if _DBISH.search(last) or last == "connect":
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def _ct042(mod: SourceModule) -> list[Finding]:
    findings: list[Finding] = []
    agent = is_agent_module(mod)
    for info in _async_functions(mod):
        conn_locals = _conn_locals(info.node) if agent else set()
        for node in _walk_no_defs(info.node):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func)
            why = None
            for prefix, reason in _BLOCKING_ASYNC.items():
                if fname == prefix or (prefix.endswith(".") and
                                       fname.startswith(prefix)):
                    why = reason
                    break
            if why is None and agent:
                last = fname.split(".")[-1] if fname else ""
                if fname == "open":
                    why = "opens a file synchronously (disk I/O on the " \
                          "serving loop)"
                elif last in _EXEC_METHODS and "." in fname:
                    recv = fname.rsplit(".", 1)[0].split(".")[-1]
                    if _DBISH.search(recv) or \
                            fname.split(".")[0] in conn_locals:
                        why = "sync sqlite on the event loop (route " \
                              "through the writer pool / an executor)"
            if why is not None:
                findings.append(Finding(
                    rule="CT042", path=mod.path, line=node.lineno,
                    col=node.col_offset,
                    message=f"`{fname}` inside `async def "
                    f"{info.qualname}`: {why}",
                ))
    return findings


# -- CT043 ---------------------------------------------------------------

def _catches_cancelled(handler: ast.ExceptHandler) -> str | None:
    """How this handler captures CancelledError, or None."""
    t = handler.type
    if t is None:
        return "bare `except:`"
    exprs = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in exprs:
        name = dotted_name(e)
        last = name.split(".")[-1]
        if last == "CancelledError":
            return f"`except {name}`"
        if last == "BaseException":
            return f"`except {name}` (CancelledError derives from it)"
    return None


def _ct043(mod: SourceModule) -> list[Finding]:
    findings: list[Finding] = []
    for info in _async_functions(mod):
        cancel_lines = [
            n.lineno for n in _walk_no_defs(info.node)
            if isinstance(n, ast.Call)
            and dotted_name(n.func).split(".")[-1] == "cancel"
        ]
        for node in _walk_no_defs(info.node):
            if not isinstance(node, ast.Try):
                continue
            # Cancel-and-await teardown: we cancelled the task ourselves
            # just above; absorbing the resulting CancelledError is the
            # documented idiom, not a swallow.
            if any(ln < node.lineno for ln in cancel_lines):
                continue
            for handler in node.handlers:
                how = _catches_cancelled(handler)
                if how is None:
                    continue
                reraises = any(
                    isinstance(n, ast.Raise)
                    for n in _walk_no_defs(handler)
                )
                if not reraises:
                    findings.append(Finding(
                        rule="CT043", path=mod.path, line=handler.lineno,
                        message=f"{how} in `async def {info.qualname}` "
                        "without re-raise — cancellation is absorbed and "
                        "shutdown/timeouts wedge; split the handler and "
                        "`raise`",
                    ))
    return findings


def check_async(mod: SourceModule) -> list[Finding]:
    return _ct040(mod) + _ct041(mod) + _ct042(mod) + _ct043(mod)
