"""Static-analysis plane: kernel-purity, schema-parity, and concurrency
lints (`corrosion lint`), plus the runtime retrace/dtype sanitizer.

The telemetry plane (sim/telemetry.py) and the convergence-health plane
(sim/health.py) observe what the kernels *do*; this package guards the
code that produces those numbers. Six pillars, each a module:

- ``purity``: AST lints over the kernel modules (``ops/`` and the
  ``sim/*engine*.py`` scan bodies) for host-trip and dtype-promotion
  hazards — the bug classes that silently retrace or slow every engine.
- ``schema``: statically extracts the telemetry keys each engine's scan
  body emits and diffs them against the canonical ``ROUND_CURVE_KEYS``,
  turning the runtime parity test into a compile-time check.
- ``concurrency``: blocking calls under held locks and lock-acquisition-
  order cycles in the host agent plane.
- ``asynclint``: asyncio race & lifecycle lints over the agent plane
  (CT040-CT043) — await-straddled state writes, fire-and-forget tasks,
  blocking calls on the event loop, swallowed CancelledError.
- ``clonemap``: the engine-clone drift gate (CT050-CT052) — the
  committed ``SEAM_MAP.json`` declares which function pairs across the
  four sim engines are intentional clones and where they legitimately
  differ; drift outside declared seams fails the lint.
- ``determinism``: determinism-taint lints (CT060-CT062) — wall clock/
  RNG/hash-order sources in traced code, the netem/fault schedule
  planes, and ``corro-*/N`` artifact emit sites.

``runner.lint_paths`` orchestrates all of them over a file tree;
``sanitize.sanitize_engines`` is the runtime companion (strict dtype
promotion + debug_nans + a one-trace-per-engine retrace tripwire). Rule
ids, rationale, and the ``# corro-lint: disable=CT0xx reason=...``
suppression syntax are documented in docs/ANALYSIS.md.

Everything except ``sanitize`` is pure stdlib (ast/tokenize) — linting
never imports jax, so `corrosion lint` stays fast and runs anywhere.
"""

from corrosion_tpu.analysis.findings import RULES, Finding  # noqa: F401
from corrosion_tpu.analysis.runner import LintResult, lint_paths  # noqa: F401
