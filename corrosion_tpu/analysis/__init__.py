"""Static-analysis plane: kernel-purity, schema-parity, and concurrency
lints (`corrosion lint`), plus the runtime retrace/dtype sanitizer.

The telemetry plane (sim/telemetry.py) and the convergence-health plane
(sim/health.py) observe what the kernels *do*; this package guards the
code that produces those numbers. Three pillars, each a module:

- ``purity``: AST lints over the kernel modules (``ops/`` and the
  ``sim/*engine*.py`` scan bodies) for host-trip and dtype-promotion
  hazards — the bug classes that silently retrace or slow every engine.
- ``schema``: statically extracts the telemetry keys each engine's scan
  body emits and diffs them against the canonical ``ROUND_CURVE_KEYS``,
  turning the runtime parity test into a compile-time check.
- ``concurrency``: blocking calls under held locks and lock-acquisition-
  order cycles in the host agent plane.

``runner.lint_paths`` orchestrates the three over a file tree;
``sanitize.sanitize_engines`` is the runtime companion (strict dtype
promotion + debug_nans + a one-trace-per-engine retrace tripwire). Rule
ids, rationale, and the ``# corro-lint: disable=CT0xx reason=...``
suppression syntax are documented in docs/ANALYSIS.md.

Everything except ``sanitize`` is pure stdlib (ast/tokenize) — linting
never imports jax, so `corrosion lint` stays fast and runs anywhere.
"""

from corrosion_tpu.analysis.findings import RULES, Finding  # noqa: F401
from corrosion_tpu.analysis.runner import LintResult, lint_paths  # noqa: F401
