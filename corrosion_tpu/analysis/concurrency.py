"""Concurrency lints for the host agent plane (CT020/CT021).

CT020 flags blocking calls (sleep, subprocess, socket dial/resolve,
file open) lexically inside ``with <lock>:`` blocks: the agent serves
its HTTP API, gossip transport, and admin RPC from one process, and a
lock held across a blocking call stalls every waiter for the call's
wall time (the reference wraps each lock in a registry precisely to
diagnose this class in production — utils/locks.py).

CT021 builds a lock-acquisition-order graph — an edge A->B when code
holding A acquires B, both lexically and through one same-class /
same-module call hop — and fails on cycles (the classic two-lock
deadlock shape). Lock identity is the dotted expression scoped by class
(``SplitPool._read_lock``), so two methods of one class share nodes but
distinct classes never alias.

Heuristics are name-based: a with-context expression counts as a lock
acquisition when its last name segment looks lock-ish (lock/mutex/
guard/sem/semaphore, e.g. ``self._read_lock``) or when it is a call to
an acquire-style method (``self._wlock(...)``, ``registry.acquire(...)``).
"""

from __future__ import annotations

import ast
import re

from corrosion_tpu.analysis.findings import Finding
from corrosion_tpu.analysis.source import SourceModule, dotted_name

_LOCKISH = re.compile(r"(?:^|_)(?:r|w)?(?:lock|mutex|guard|sem|semaphore)s?$",
                      re.IGNORECASE)
_ACQUIRISH = re.compile(r"(?:^|_)(?:acquire|wlock|rlock)$", re.IGNORECASE)

# dotted-prefix -> why it blocks. Matching is by module root + attr.
_BLOCKING = {
    "time.sleep": "sleeps while holding the lock",
    "subprocess.": "spawns and waits on a child process",
    "os.system": "spawns a shell and waits",
    "os.popen": "spawns a shell",
    "socket.create_connection": "dials a TCP connection",
    "socket.getaddrinfo": "resolves DNS",
    "socket.gethostbyname": "resolves DNS",
    "requests.": "performs a blocking HTTP request",
    "urllib.request.": "performs a blocking HTTP request",
    "open": "opens a file (disk I/O)",
}


def _lock_identity(item: ast.withitem, class_name: str | None) -> str | None:
    """Dotted lock identity for one with-item, or None if not a lock."""
    expr = item.context_expr
    name = dotted_name(expr)
    if isinstance(expr, ast.Call):
        fname = dotted_name(expr.func)
        last = fname.split(".")[-1] if fname else ""
        if _ACQUIRISH.search(last):
            name = fname
        elif last == "acquire" and len(fname.split(".")) > 1:
            # registry.acquire(lock, label): identity = the lock argument
            # when nameable, else the registry expression.
            name = (
                dotted_name(expr.args[0]) if expr.args else ""
            ) or fname
        else:
            return None
    if not name:
        return None
    last = name.split(".")[-1]
    if not (_LOCKISH.search(last) or _ACQUIRISH.search(last)):
        return None
    if name.startswith("self.") and class_name:
        return f"{class_name}.{name[5:]}"
    return name


def _blocking_reason(call: ast.Call) -> str | None:
    fname = dotted_name(call.func)
    if not fname:
        return None
    for prefix, why in _BLOCKING.items():
        if fname == prefix or (prefix.endswith(".") and
                               fname.startswith(prefix)):
            return why
    return None


def _walk_no_defs(node: ast.AST):
    """Walk a body without descending into nested function/class defs
    (their bodies execute later, outside the held lock)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            continue
        yield child
        yield from _walk_no_defs(child)


def check_concurrency(mod: SourceModule) -> list[Finding]:
    findings: list[Finding] = []
    edges: dict[tuple[str, str], int] = {}  # (a, b) -> first line

    # class context per function: qualname prefix ending in ClassName.
    class_of: dict[ast.AST, str | None] = {}

    def assign_classes(node: ast.AST, cls: str | None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                assign_classes(child, child.name)
            else:
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    class_of[child] = cls
                assign_classes(child, cls)

    assign_classes(mod.tree, None)

    # locks each function/method acquires anywhere in its body (for the
    # one-hop call propagation), keyed by (class, name) and (None, name).
    acquired_by: dict[tuple[str | None, str], set[str]] = {}
    funcs: list[tuple[ast.AST, str | None]] = [
        (f, class_of.get(f)) for f in ast.walk(mod.tree)
        if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for f, cls in funcs:
        acq: set[str] = set()
        for node in _walk_no_defs(f):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lock = _lock_identity(item, cls)
                    if lock:
                        acq.add(lock)
        acquired_by[(cls, f.name)] = acq
        acquired_by.setdefault((None, f.name), set()).update(acq)

    def scan_with(node: ast.AST, held: list[str], cls: str | None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            now_held = held
            if isinstance(child, (ast.With, ast.AsyncWith)):
                locks = [
                    lk for item in child.items
                    if (lk := _lock_identity(item, cls))
                ]
                for lk in locks:
                    for h in held:
                        if h != lk:
                            edges.setdefault((h, lk), child.lineno)
                now_held = held + locks
            if held and isinstance(child, ast.Call):
                why = _blocking_reason(child)
                if why:
                    findings.append(Finding(
                        rule="CT020", path=mod.path, line=child.lineno,
                        col=child.col_offset,
                        message=f"`{dotted_name(child.func)}` under held "
                        f"lock {held[-1]}: {why}; move it outside the "
                        "critical section",
                    ))
                # one-hop: calling a method/function that itself
                # acquires locks while we hold one.
                fname = dotted_name(child.func)
                callee: set[str] = set()
                if fname.startswith("self."):
                    callee = acquired_by.get(
                        (cls, fname.split(".")[-1]), set()
                    )
                elif fname and "." not in fname:
                    callee = acquired_by.get((None, fname), set())
                for lk in callee:
                    for h in held:
                        if h != lk:
                            edges.setdefault((h, lk), child.lineno)
            scan_with(child, now_held, cls)

    for f, cls in funcs:
        scan_with(f, [], cls)

    # Cycle detection over the acquisition-order graph.
    graph: dict[str, set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    state: dict[str, int] = {}  # 0 visiting, 1 done
    reported: set[frozenset] = set()

    def dfs(node: str, stack: list[str]):
        state[node] = 0
        for nxt in sorted(graph.get(node, ())):
            if state.get(nxt) == 0:
                cycle = stack[stack.index(nxt):] + [nxt] if nxt in stack \
                    else [node, nxt]
                key = frozenset(cycle)
                if key not in reported:
                    reported.add(key)
                    line = min(
                        ln for (a, b), ln in edges.items()
                        if a in key and b in key
                    )
                    findings.append(Finding(
                        rule="CT021", path=mod.path, line=line, col=0,
                        message="lock-acquisition-order cycle: "
                        + " -> ".join(cycle)
                        + " (latent deadlock; fix the ordering)",
                    ))
            elif nxt not in state:
                dfs(nxt, stack + [nxt])
        state[node] = 1

    for n in sorted(graph):
        if n not in state:
            dfs(n, [n])
    return findings
