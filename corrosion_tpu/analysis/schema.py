"""Schema-parity lint (CT010): engines' emitted telemetry keys vs the
canonical ``ROUND_CURVE_KEYS`` — statically.

All four engines' scan bodies must emit exactly the canonical RoundCurves
key set (sim/telemetry.py zero-fills the rest, so the *final* dict is
always canonical — what can drift is an engine passing an unknown key,
which today raises only at trace time, i.e. after a run was launched,
possibly hours into a queue slot). This module turns that runtime
ValueError into a lint: it extracts the canonical tuples from
telemetry.py without importing it (no jax), finds every
``round_curves(...)`` call site, resolves its keywords — including
``**delivery_latency_hist(...)`` / ``**prop_curves(...)`` /
``**link_curves(...)`` expansions through one local-assignment hop —
and diffs.

The restricted evaluator executes only top-level ``NAME = <expr>``
assignments from telemetry.py against a tuple/range/len-only builtin
namespace; anything it can't evaluate is skipped, and a telemetry.py
refactor that breaks extraction fails loudly (CT010 on the runner).
"""

from __future__ import annotations

import ast

from corrosion_tpu.analysis.findings import Finding
from corrosion_tpu.analysis.source import SourceModule, dotted_name

_EVAL_BUILTINS = {"tuple": tuple, "range": range, "len": len,
                  "sorted": sorted, "set": set, "frozenset": frozenset}


def extract_canonical(telemetry_path: str) -> dict[str, tuple]:
    """Evaluate telemetry.py's top-level key tuples without importing it.

    Returns the module-level constants that evaluated cleanly (expected:
    VIS_LAT_EDGES, VIS_LAT_KEYS, HEALTH_CURVE_KEYS, ROUND_CURVE_KEYS,
    LEVEL_CURVE_KEYS, plus the propagation plane's LINK_CURVE_KEYS /
    RUMOR_AGE_KEYS / PROP_CURVE_KEYS). tests/test_analysis.py pins this
    against the imported module so the evaluator can never silently
    drift.
    """
    with open(telemetry_path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=telemetry_path)
    env: dict[str, object] = {}
    for node in tree.body:
        if not (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            continue
        name = node.targets[0].id
        try:
            code = compile(ast.Expression(node.value), telemetry_path, "eval")
            env[name] = eval(  # noqa: S307 - restricted namespace
                code, {"__builtins__": _EVAL_BUILTINS, **env}
            )
        except Exception:
            continue
    return {
        k: v for k, v in env.items()
        if isinstance(v, tuple) and all(isinstance(e, (str, int)) for e in v)
    }


def _resolve_star(mod: SourceModule, call: ast.Call, star: ast.AST,
                  canonical: dict[str, tuple]) -> tuple | None:
    """Keys contributed by a ``**expr`` in a round_curves call: a direct
    call to one of the telemetry key-set helpers
    (``delivery_latency_hist`` → VIS_LAT_KEYS, ``prop_curves`` →
    PROP_CURVE_KEYS, ``link_curves`` → LINK_CURVE_KEYS) or one hop
    through a local ``name = <helper>(...)`` assignment in the
    enclosing function. None = statically unresolvable."""
    helpers = {
        "delivery_latency_hist": tuple(canonical.get("VIS_LAT_KEYS", ())),
        "prop_curves": tuple(canonical.get("PROP_CURVE_KEYS", ())),
        "link_curves": tuple(canonical.get("LINK_CURVE_KEYS", ())),
    }

    def helper_keys(expr: ast.AST) -> tuple | None:
        if not isinstance(expr, ast.Call):
            return None
        return helpers.get(dotted_name(expr.func).split(".")[-1])

    got = helper_keys(star)
    if got is not None:
        return got
    if isinstance(star, ast.Name):
        fn = mod.enclosing_function(call)
        scope = fn.node if fn is not None else mod.tree
        for node in ast.walk(scope):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == star.id
            ):
                got = helper_keys(node.value)
                if got is not None:
                    return got
    return None


def emitted_keys(
    mod: SourceModule, canonical: dict[str, tuple]
) -> tuple[list[str], list[Finding]]:
    """(sorted emitted key set, findings) for one module's
    ``round_curves(...)`` call sites."""
    keys: set[str] = set()
    findings: list[Finding] = []
    canon = set(canonical.get("ROUND_CURVE_KEYS", ()))
    calls = [
        node for node in ast.walk(mod.tree)
        if isinstance(node, ast.Call)
        and dotted_name(node.func).split(".")[-1] == "round_curves"
    ]
    for call in calls:
        for kw in call.keywords:
            if kw.arg is None:
                got = _resolve_star(mod, call, kw.value, canonical)
                if got is None:
                    findings.append(Finding(
                        rule="CT010", path=mod.path, line=kw.value.lineno,
                        col=kw.value.col_offset,
                        message="`**` expansion in round_curves(...) is "
                        "not statically resolvable; emit "
                        "delivery_latency_hist directly (or via one "
                        "local assignment) so parity stays checkable",
                    ))
                else:
                    keys.update(got)
                continue
            keys.add(kw.arg)
            if canon and kw.arg not in canon:
                findings.append(Finding(
                    rule="CT010", path=mod.path, line=call.lineno,
                    col=call.col_offset,
                    message=f"round_curves key '{kw.arg}' is not in the "
                    "canonical ROUND_CURVE_KEYS set (runtime would "
                    "ValueError at trace time)",
                ))
    if mod.is_engine and not calls:
        findings.append(Finding(
            rule="CT010", path=mod.path, line=1, col=0,
            message="engine module never builds its per-round stats "
            "through telemetry.round_curves(...) — the schema parity "
            "contract is unenforceable here",
        ))
    return sorted(keys), findings
