"""Finding model and the CT0xx rule registry.

Every rule has a stable id so suppressions (``# corro-lint:
disable=CT003 reason=...``), CI gating, and the JSON report format stay
meaningful as rules are added. What each violation costs on TPU is
documented per rule in docs/ANALYSIS.md.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field

# rule id -> (title, one-line rationale). The long-form rationale (what
# the violation costs at kernel scale) lives in docs/ANALYSIS.md.
RULES: dict[str, tuple[str, str]] = {
    "CT000": (
        "bad-suppression",
        "corro-lint suppression without a reason= string or naming an "
        "unknown rule id",
    ),
    "CT009": (
        "stale-suppression",
        "a corro-lint suppression whose rule no longer fires on its "
        "line/scope — delete it so the suppression inventory can't rot "
        "(non-gating; listed under --show-suppressed)",
    ),
    "CT001": (
        "numpy-in-traced-code",
        "numpy (np.*) usage inside a traced kernel function — a host "
        "round-trip that blocks the device per call if it ever touches "
        "a traced value",
    ),
    "CT002": (
        "local-numpy-import",
        "function-local `import numpy` in a kernel module — hoist to "
        "module scope or suppress with a reason",
    ),
    "CT003": (
        "dtypeless-jnp-literal",
        "jnp.array/zeros/ones/full/empty without an explicit dtype in a "
        "kernel module — promotion drift changes downstream widths",
    ),
    "CT004": (
        "traced-value-coercion",
        "float()/int()/bool()/.item()/.tolist() in a traced kernel "
        "function — forces a device sync per call",
    ),
    "CT005": (
        "python-branch-on-traced",
        "Python if/while on a traced parameter of a scan-body or jitted "
        "function — retraces per value or raises TracerBoolConversion",
    ),
    "CT010": (
        "round-curve-schema",
        "engine scan body emits a telemetry key outside the canonical "
        "ROUND_CURVE_KEYS set (or its emission cannot be statically "
        "resolved)",
    ),
    "CT020": (
        "blocking-call-under-lock",
        "blocking call (sleep/subprocess/socket/file I/O) inside a "
        "`with <lock>:` block — stalls every waiter for the call's wall",
    ),
    "CT021": (
        "lock-order-cycle",
        "cycle in the lock-acquisition-order graph — a latent deadlock",
    ),
    "CT040": (
        "await-straddled-state-write",
        "an async method reads a shared `self` attribute, suspends at an "
        "await, then writes it back without holding the guarding lock — "
        "a concurrent task can interleave at the await and the write "
        "clobbers its update (the PR-14 wedge-bug shape)",
    ),
    "CT041": (
        "fire-and-forget-task",
        "create_task/ensure_future whose result is neither stored, "
        "awaited, nor given add_done_callback — the task can die "
        "silently (exceptions vanish) or be garbage-collected mid-run",
    ),
    "CT042": (
        "blocking-call-in-async",
        "blocking call (sleep/subprocess/socket dial/sync sqlite/file "
        "open) lexically inside an `async def` — stalls the event loop "
        "for the call's wall time; every session on the loop waits",
    ),
    "CT043": (
        "cancellederror-swallowed",
        "an except handler in an `async def` catches "
        "asyncio.CancelledError (directly, bare, or via BaseException) "
        "without re-raising — cancellation is absorbed and "
        "shutdown/timeouts wedge",
    ),
    "CT050": (
        "engine-clone-drift",
        "an intentional engine-clone pair declared in SEAM_MAP.json "
        "diverges outside its declared seams — the four-copy round "
        "stanza drifted (the bug class CT010/parity runtime tests exist "
        "to catch after the fact)",
    ),
    "CT051": (
        "seam-map-function-missing",
        "a function mapped in SEAM_MAP.json no longer exists — update "
        "the map (deleting entries is the ROADMAP item-4 progress "
        "metric, but it must be deliberate)",
    ),
    "CT052": (
        "partial-plane-coverage",
        "a canonical round-curve key is emitted by some but not all "
        "four engines and carries no seam-map waiver — a new per-round "
        "plane was threaded through fewer than four copies",
    ),
    "CT060": (
        "nondeterminism-in-traced-code",
        "wall clock/random/uuid/os.urandom or set-order iteration "
        "inside a traced kernel function — the value is baked at trace "
        "time and differs per process, breaking replay and retrace "
        "stability",
    ),
    "CT061": (
        "nondeterminism-in-schedule-module",
        "nondeterministic source in a deterministic-schedule module "
        "(agent/netem.py, sim/faults.py) — impairment and fault "
        "schedules must be pure functions of seed+coordinates or exact "
        "replay breaks",
    ),
    "CT062": (
        "nondeterminism-at-artifact-emit",
        "nondeterministic source in a function that emits a "
        "`corro-*/N` artifact — committed artifacts must be "
        "byte-deterministic for baseline diff gates to mean anything",
    ),
    "CT030": (
        "retrace-tripwire",
        "sanitizer: an engine's scanned round compiled more than once "
        "across same-shape chunks (silent retrace)",
    ),
    "CT031": (
        "strict-dtype-violation",
        "sanitizer: engine fails under "
        "jax_numpy_dtype_promotion='strict' (implicit promotion in the "
        "round graph)",
    ),
    "CT032": (
        "nan-produced",
        "sanitizer: engine produced a NaN under jax_debug_nans",
    ),
    "CT033": (
        "sanitizer-run-failure",
        "sanitizer: engine run failed for a reason other than dtype "
        "promotion or NaNs (the tiny-config run itself is broken)",
    ),
}


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str
    col: int = 0
    suppressed: bool = False
    suppress_reason: str = ""

    def render(self) -> str:
        title = RULES.get(self.rule, ("?",))[0]
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"[{title}] {self.message}"
        )


@dataclass
class LintResult:
    """Outcome of a lint run: active findings gate, suppressed ones are
    kept for transparency, per-engine emitted key sets feed the schema
    tests and the JSON artifact."""

    findings: list[Finding] = field(default_factory=list)
    suppressed: list[Finding] = field(default_factory=list)
    stale: list[Finding] = field(default_factory=list)  # CT009, non-gating
    files: int = 0
    engines: dict[str, list[str]] = field(default_factory=dict)
    canonical_keys: tuple[str, ...] = ()

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "ok": self.ok,
            "files": self.files,
            "findings": [asdict(f) for f in self.findings],
            "suppressed": [asdict(f) for f in self.suppressed],
            "stale_suppressions": [asdict(f) for f in self.stale],
            "engines": self.engines,
            "canonical_keys": list(self.canonical_keys),
            "rules": {k: {"title": t, "why": w} for k, (t, w) in RULES.items()},
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    def render_text(self, show_suppressed: bool = False) -> str:
        lines = [f.render() for f in self.findings]
        if show_suppressed:
            for f in self.suppressed:
                lines.append(
                    f"{f.render()}  (suppressed: {f.suppress_reason})"
                )
            for f in self.stale:
                lines.append(f"{f.render()}  (non-gating)")
        lines.append(
            f"{len(self.findings)} finding(s), "
            f"{len(self.suppressed)} suppressed, "
            f"{len(self.stale)} stale suppression(s), {self.files} file(s)"
        )
        return "\n".join(lines)
