"""Shared source model for the lint rules.

``SourceModule`` parses one file and answers the questions every rule
needs: which functions are *traced* (jit-decorated, scan/cond/while
bodies, or anything nested in one — the code where a host trip or a
Python branch on a traced value is a real hazard), which parameters are
static under jit, and which findings are suppressed by
``# corro-lint: disable=CT0xx reason=...`` comments.

Kernel-module classification is path-based (``ops/`` and the
``sim/*engine*.py`` drivers) with a marker-comment escape hatch
(``# corro-lint: kernel-module`` / ``# corro-lint: engine-module``) so
test fixtures outside the package opt in explicitly. In ``ops/``
modules every function is PRESUMED traced: the package is the kernel
namespace, and host-side helpers (topology builders, ground-truth
references) must carry a reasoned suppression — that asymmetry is the
point, host code in the kernel namespace should be loud.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field

SUPPRESS_RE = re.compile(
    r"#\s*corro-lint:\s*disable=([A-Z0-9,\s]+?)\s*(?:reason=(.+))?$"
)
# Marker comments must stand alone on a line: matching the bare substring
# would self-trigger on any file that mentions the marker (this one).
KERNEL_MARKER = re.compile(r"(?m)^\s*#\s*corro-lint:\s*kernel-module\s*$")
ENGINE_MARKER = re.compile(r"(?m)^\s*#\s*corro-lint:\s*engine-module\s*$")

# sim drivers whose scan bodies emit the canonical RoundCurves schema.
ENGINE_FILES = ("engine.py", "sparse_engine.py", "chunk_engine.py",
                "mixed_engine.py")

# jax control-flow primitives whose function arguments run inside the
# trace: any locally-defined function passed to one is a traced body.
_TRACING_CALLS = ("scan", "cond", "while_loop", "fori_loop", "map",
                  "switch", "associative_scan")


def dotted_name(node: ast.AST) -> str:
    """Best-effort dotted name of an expression ('jax.lax.scan',
    'self._read_lock', ...); '' when it isn't a plain name chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


@dataclass
class Suppression:
    line: int
    rules: set[str]
    reason: str


@dataclass
class FunctionInfo:
    node: ast.AST
    qualname: str
    parent: "FunctionInfo | None"
    traced: bool = False
    traced_why: str = ""  # 'jit' | 'scan-body' | 'nested' | 'presumed'
    static_params: set[str] = field(default_factory=set)

    @property
    def explicit_traced(self) -> bool:
        """Traced by construction (jit/scan-body/nested), not by the
        ops-namespace presumption — the set CT005 branches on."""
        return self.traced and self.traced_why != "presumed"


def _static_argnames(call: ast.Call) -> set[str]:
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums") and isinstance(
            kw.value, (ast.Tuple, ast.List)
        ):
            return {
                e.value for e in kw.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            }
    return set()


def _jit_decoration(dec: ast.AST) -> tuple[bool, set[str]]:
    """(is_jit, static_argnames) for one decorator expression. Handles
    ``@jax.jit``, ``@jit``, ``@partial(jax.jit, static_argnames=...)``
    and ``@jax.jit(...)`` forms."""
    name = dotted_name(dec)
    if name in ("jit", "jax.jit"):
        return True, set()
    if isinstance(dec, ast.Call):
        fname = dotted_name(dec.func)
        if fname in ("jit", "jax.jit"):
            return True, _static_argnames(dec)
        if fname in ("partial", "functools.partial") and dec.args:
            inner = dotted_name(dec.args[0])
            if inner in ("jit", "jax.jit"):
                return True, _static_argnames(dec)
    return False, set()


class SourceModule:
    def __init__(self, path: str, text: str | None = None):
        self.path = path
        if text is None:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        self.text = text
        self.tree = ast.parse(text, filename=path)
        self.is_kernel = self._classify_kernel()
        self.is_engine = self._classify_engine()
        self.suppressions: list[Suppression] = []
        self.bad_suppressions: list[tuple[int, str]] = []
        self._parse_suppressions()
        self.functions: list[FunctionInfo] = []
        self._func_of: dict[ast.AST, FunctionInfo] = {}
        self._classify_functions()

    # -- module classification ------------------------------------------

    def _classify_kernel(self) -> bool:
        parts = self.path.replace("\\", "/").split("/")
        if KERNEL_MARKER.search(self.text) or ENGINE_MARKER.search(self.text):
            return True
        if "ops" in parts[:-1]:
            return True
        return parts[-1] in ENGINE_FILES and "sim" in parts[:-1]

    def _classify_engine(self) -> bool:
        parts = self.path.replace("\\", "/").split("/")
        if ENGINE_MARKER.search(self.text):
            return True
        return parts[-1] in ENGINE_FILES and "sim" in parts[:-1]

    @property
    def presume_traced(self) -> bool:
        """ops/ modules (and kernel-marked fixtures): every function is
        kernel code unless a suppression says otherwise."""
        return self.is_kernel and not self.is_engine

    # -- suppressions ---------------------------------------------------

    def _parse_suppressions(self) -> None:
        from corrosion_tpu.analysis.findings import RULES

        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.text).readline)
            comments = [
                (t.start[0], t.string) for t in tokens
                if t.type == tokenize.COMMENT
            ]
        except tokenize.TokenError:
            comments = []
        for line, comment in comments:
            m = SUPPRESS_RE.search(comment)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = (m.group(2) or "").strip()
            unknown = sorted(r for r in rules if r not in RULES)
            if unknown:
                self.bad_suppressions.append(
                    (line, f"unknown rule id(s) {unknown} in suppression")
                )
                continue
            if not reason:
                self.bad_suppressions.append(
                    (line, "suppression without a reason= string "
                     "(reasons are mandatory; the suppression is ignored)")
                )
                continue
            self.suppressions.append(Suppression(line, rules, reason))

    def suppression_for(self, rule: str, line: int) -> Suppression | None:
        """Line-level suppression at ``line``, or a scope-level one from
        the header zone (decorators/def line, or the line just above) of
        any enclosing function/class."""
        for s in self.suppressions:
            if rule in s.rules and s.line == line:
                return s
        for node in ast.walk(self.tree):
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            end = getattr(node, "end_lineno", node.lineno)
            if not (node.lineno <= line <= end):
                continue
            first = min(
                [node.lineno] + [d.lineno for d in node.decorator_list]
            )
            header = range(first - 1, node.body[0].lineno)
            for s in self.suppressions:
                if rule in s.rules and s.line in header:
                    return s
        return None

    # -- traced-function classification ---------------------------------

    def _classify_functions(self) -> None:
        # Pass 1: collect functions with parent links; jit decorations.
        def visit(node: ast.AST, parent: FunctionInfo | None, prefix: str):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{prefix}{child.name}"
                    info = FunctionInfo(child, qual, parent)
                    for dec in child.decorator_list:
                        is_jit, statics = _jit_decoration(dec)
                        if is_jit:
                            info.traced = True
                            info.traced_why = "jit"
                            info.static_params |= statics
                    self.functions.append(info)
                    self._func_of[child] = info
                    visit(child, info, qual + ".")
                elif isinstance(child, ast.ClassDef):
                    visit(child, parent, f"{prefix}{child.name}.")
                else:
                    visit(child, parent, prefix)

        visit(self.tree, None, "")

        # Pass 2: functions handed to jax control-flow primitives are
        # traced bodies. Resolve Name arguments to local defs by scope.
        by_name: dict[str, list[FunctionInfo]] = {}
        for info in self.functions:
            by_name.setdefault(info.node.name, []).append(info)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func)
            if fname.split(".")[-1] not in _TRACING_CALLS or (
                "." in fname and "lax" not in fname and "jax" not in fname
            ):
                continue
            if fname == "map":
                continue  # builtin map(), not lax.map (dotted)
            for arg in node.args:
                if isinstance(arg, ast.Name) and arg.id in by_name:
                    for cand in by_name[arg.id]:
                        if not cand.traced:
                            cand.traced = True
                            cand.traced_why = "scan-body"

        # Pass 3: propagate — nested inside traced => traced; ops/
        # presumption marks everything else.
        changed = True
        while changed:
            changed = False
            for info in self.functions:
                if not info.traced and info.parent and info.parent.traced:
                    info.traced = True
                    info.traced_why = "nested"
                    changed = True
        if self.presume_traced:
            for info in self.functions:
                if not info.traced:
                    info.traced = True
                    info.traced_why = "presumed"

    def enclosing_function(self, node: ast.AST) -> FunctionInfo | None:
        """FunctionInfo whose body lexically contains ``node`` (innermost)."""
        best: FunctionInfo | None = None
        line = getattr(node, "lineno", None)
        if line is None:
            return None
        for info in self.functions:
            f = info.node
            end = getattr(f, "end_lineno", f.lineno)
            if f.lineno <= line <= end:
                if best is None or f.lineno >= best.node.lineno:
                    best = info
        return best
