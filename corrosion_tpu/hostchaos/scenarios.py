"""The standing host-chaos scenarios (docs/CHAOS.md "Host plane").

Each is a :class:`~corrosion_tpu.hostchaos.harness.HostScenario`: a
``corro-host-fault-plan/1`` over the transport planes, a write storm +
oracle-checked subscriptions, optional SIGKILL-then-restart, and the
list of defenses the scenario is BUILT to force (``require_fired``) —
windows and agent knobs are tuned together so each required counter is
mechanically guaranteed to tick on a 2-vCPU CI box:

- ``wan_steady``: the 80 ms-RTT / 1 %-loss WAN baseline. Nothing is cut;
  the invariant under test is that ordinary WAN impairment alone causes
  zero oracle violations and full convergence.
- ``partition_heal``: n3 cut from the cluster, then healed into a slow
  sync window. Forces breaker trips (cut link), chunk halvings (sync
  sends slower than the adapt threshold), and stall aborts (sends
  slower than the stall timeout) during n3's catch-up.
- ``link_flap``: one node's links toggle every 0.7 s. Forces breaker
  trips AND recoveries — the flap cadence sits exactly where a breaker
  without success-reset would wedge the link permanently.
- ``kill_restart``: SIGKILL mid-storm (no graceful leave), same-dir
  restart. Forces breaker trips (connection-refused bursts at the dead
  peer) and proves store rehydration + durable-subscription resume.
- ``wan_full``: the acceptance scenario — WAN steady-state impairment +
  partition-then-heal + SIGKILL-then-restart in ONE run, all three
  headline defenses required to fire.
- ``flap_soak``: the long flap/partition churn soak (slow-marked out of
  the tier-1 lane and the CI smoke; the chaos job and `hostchaos run`
  territory).
"""

from __future__ import annotations

from corrosion_tpu.agent.netem import HostFault, HostFaultPlan
from corrosion_tpu.hostchaos.harness import HostScenario, KillSpec

# Chaos-compressed agent knobs shared by every scenario: faster probe /
# sync cadence and a sub-second breaker schedule so seconds-long fault
# windows exercise machinery tuned for minutes-long production faults.
_BASE_CFG = dict(
    probe_interval=0.2,
    sync_interval=0.4,
    breaker_base_s=0.5,
    breaker_max_s=2.0,
    announce_backoff_min_s=0.5,
    announce_backoff_max_s=4.0,
    member_persist_interval=2.0,
)

# Sync-defense knobs for scenarios that force the chunker/stall guard:
# halving window sends (~330 ms) sit above the adapt threshold, stall
# window sends (~2.4 s) above the stall timeout.
_SYNC_DEFENSE_CFG = dict(
    _BASE_CFG, sync_adapt_threshold=0.15, sync_stall_timeout=1.2,
)


def _wan(delay_ms: float = 40.0, jitter_ms: float = 10.0,
         loss: float = 0.01) -> tuple:
    """Always-on WAN baseline: one-way delay ± jitter on every plane
    (2x delay ≈ the RTT) + loss on the lossy planes."""
    comps = [HostFault(kind="delay", delay_ms=delay_ms, jitter_ms=jitter_ms)]
    if loss > 0:
        comps.append(
            HostFault(kind="loss", prob=loss, planes=("probe", "bcast"))
        )
    return tuple(comps)


def wan_steady() -> HostScenario:
    return HostScenario(
        name="wan_steady",
        plan=HostFaultPlan(name="wan_steady", faults=_wan()),
        n_agents=3, writes=36, write_rate=8.0, subs=9, sub_groups=3,
        agent_cfg=dict(_BASE_CFG),
        require_fired=(),
        notes="80 ms RTT ± jitter, 1% loss; oracle + convergence only",
    )


def partition_heal() -> HostScenario:
    plan = HostFaultPlan(
        name="partition_heal",
        faults=_wan(10.0, 3.0, 0.0) + (
            HostFault(kind="partition", a=("n3",), start_s=0.5,
                      stop_s=2.5, stall_s=0.25),
            HostFault(kind="delay", planes=("sync",), start_s=2.5,
                      stop_s=6.0, delay_ms=320.0, jitter_ms=40.0),
            HostFault(kind="delay", planes=("sync",), start_s=6.0,
                      stop_s=7.5, delay_ms=2400.0),
        ),
    )
    return HostScenario(
        name="partition_heal",
        plan=plan,
        n_agents=4, writes=70, write_rate=10.0, subs=9, sub_groups=3,
        agent_cfg=dict(_SYNC_DEFENSE_CFG),
        require_fired=("breaker_trips", "chunk_halvings", "stall_aborts"),
        notes="cut n3, heal into a slow-sync window, then a stalled one",
    )


def link_flap() -> HostScenario:
    plan = HostFaultPlan(
        name="link_flap",
        faults=_wan(20.0, 5.0, 0.0) + (
            HostFault(kind="flap", a=("n2",), start_s=0.5, stop_s=4.7,
                      period_s=0.7, stall_s=0.12),
        ),
    )
    return HostScenario(
        name="link_flap",
        plan=plan,
        n_agents=3, writes=40, write_rate=8.0, subs=9, sub_groups=3,
        agent_cfg=dict(_BASE_CFG),
        require_fired=("breaker_trips", "breaker_recoveries"),
        notes="n2's links toggle every 0.7 s: trips AND recoveries",
    )


def kill_restart() -> HostScenario:
    return HostScenario(
        name="kill_restart",
        plan=HostFaultPlan(name="kill_restart"),  # no netem: pure crash
        n_agents=3, writes=50, write_rate=10.0, subs=9, sub_groups=3,
        subs_on=0,
        kill=KillSpec(agent=0, t_kill_s=1.5, t_restart_s=2.7),
        agent_cfg=dict(_BASE_CFG),
        require_fired=("breaker_trips",),
        notes="SIGKILL n0 mid-storm (subs live on it), same-dir restart",
    )


def wan_full() -> HostScenario:
    """The acceptance scenario (ISSUE 14): WAN steady-state + partition-
    then-heal + SIGKILL-then-restart in one seeded run; stall abort,
    chunk halving, and breaker trip must all fire."""
    plan = HostFaultPlan(
        name="wan_full",
        faults=_wan(40.0, 10.0, 0.01) + (
            HostFault(kind="partition", a=("n2",), start_s=2.0,
                      stop_s=4.0, stall_s=0.25),
            HostFault(kind="delay", planes=("sync",), start_s=4.0,
                      stop_s=7.5, delay_ms=320.0, jitter_ms=40.0),
            HostFault(kind="delay", planes=("sync",), start_s=7.5,
                      stop_s=9.0, delay_ms=2400.0),
        ),
    )
    return HostScenario(
        name="wan_full",
        plan=plan,
        n_agents=4, writes=90, write_rate=10.0, subs=12, sub_groups=3,
        subs_on=0,
        kill=KillSpec(agent=0, t_kill_s=3.0, t_restart_s=4.2),
        agent_cfg=dict(_SYNC_DEFENSE_CFG),
        require_fired=("breaker_trips", "chunk_halvings", "stall_aborts"),
        drain_timeout_s=60.0,
        notes="80 ms WAN + 1% loss + partition-heal + SIGKILL-restart",
    )


def flap_soak() -> HostScenario:
    """Long churn soak: minutes-scale flapping + repeated partitions
    under WAN impairment. Slow-marked out of tier-1 AND the CI smoke."""
    plan = HostFaultPlan(
        name="flap_soak",
        faults=_wan(30.0, 8.0, 0.01) + (
            HostFault(kind="flap", a=("n1",), start_s=1.0, stop_s=12.0,
                      period_s=0.9, stall_s=0.12),
            HostFault(kind="partition", a=("n2",), start_s=13.0,
                      stop_s=15.5, stall_s=0.25),
            HostFault(kind="flap", a=("n2",), start_s=17.0, stop_s=24.0,
                      period_s=1.1, stall_s=0.12),
        ),
    )
    return HostScenario(
        name="flap_soak",
        plan=plan,
        n_agents=3, writes=200, write_rate=8.0, subs=9, sub_groups=3,
        agent_cfg=dict(_BASE_CFG),
        require_fired=("breaker_trips", "breaker_recoveries"),
        drain_timeout_s=90.0,
        notes="25 s of flap/partition churn under WAN impairment (soak)",
    )


def soak_churn(scale: float = 1.0) -> HostScenario:
    """The ENDURANCE soak composition (docs/OBSERVABILITY.md "Endurance
    plane"): churn (SIGKILL + same-dir restart) + write storm + WAN
    netem over a CI-sized horizon, run with the metric-series recorder
    armed (``run_scenario(series_dir=...)``) so every agent's registry
    movement feeds the leak/wedge/stall/SLO detectors. ``scale``
    stretches the horizon for the slow-marked long variant (the storm,
    fault windows, and kill schedule all scale together; rates stay
    fixed so total traffic grows with the horizon)."""
    s = scale
    plan = HostFaultPlan(
        name="soak_churn",
        faults=_wan(30.0, 8.0, 0.01) + (
            HostFault(kind="flap", a=("n1",), start_s=1.0 * s,
                      stop_s=4.0 * s, period_s=0.7, stall_s=0.12),
            HostFault(kind="delay", planes=("sync",), start_s=5.0 * s,
                      stop_s=7.0 * s, delay_ms=280.0, jitter_ms=40.0),
        ),
    )
    return HostScenario(
        name="soak_churn",
        plan=plan,
        n_agents=3, writes=int(80 * s), write_rate=10.0,
        subs=9, sub_groups=3, subs_on=0,
        kill=KillSpec(agent=0, t_kill_s=2.0 * s, t_restart_s=3.2 * s),
        agent_cfg=dict(_BASE_CFG),
        require_fired=("breaker_trips", "breaker_recoveries"),
        drain_timeout_s=60.0 * max(1.0, s),
        notes="WAN + flap churn + SIGKILL-restart with the metric-series "
              "recorder armed: the standing endurance lane",
    )


SCENARIOS = {
    "wan_steady": wan_steady,
    "partition_heal": partition_heal,
    "link_flap": link_flap,
    "kill_restart": kill_restart,
    "wan_full": wan_full,
    "flap_soak": flap_soak,
    "soak_churn": soak_churn,
}


def get_scenario(name: str) -> HostScenario:
    try:
        return SCENARIOS[name]()
    except KeyError:
        raise ValueError(
            f"unknown host-chaos scenario {name!r}; one of "
            f"{sorted(SCENARIOS)}"
        ) from None
