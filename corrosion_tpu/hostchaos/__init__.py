"""Host-plane chaos: deterministic WAN fault injection against real
agents (docs/CHAOS.md "Host plane").

The kernel plane's chaos subsystem (sim/faults.py + sim/invariants.py)
proves the SIMULATED protocol heals; this package proves the HOST
implementation does — the sync stall abort, adaptive chunk halving,
per-peer circuit breaker, announcer backoff, and durable-subscription
resume, all exercised under a seeded network-impairment schedule
(agent/netem.py) composed with the loadgen write storm and fan-out
oracle, ending in post-heal invariants AND a mechanical proof that the
defensive machinery actually fired.
"""

from corrosion_tpu.hostchaos.harness import (  # noqa: F401
    HostScenario,
    KillSpec,
    MACHINERY,
    run_scenario,
)
from corrosion_tpu.hostchaos.scenarios import SCENARIOS, get_scenario  # noqa: F401
