"""Host-chaos report emit path + the ``hostchaos`` budget gate.

Same contract as the serving plane's (loadgen/report.py): every report
funnels through ``telemetry.check_bench_invariants`` with ``scenario``
provenance, and :func:`check_hostchaos_budget` gates the CI smoke
against the ``hostchaos`` entry of bench_budget.json. Two classes of
key are NEVER tolerance-scaled:

- ``oracle_violations_max`` (default 0): exactly-once delivery and
  change-id monotonicity under chaos are correctness, not performance;
- ``require_machinery_fired`` / ``require_converged``: a scenario whose
  forced defenses stayed idle, or that ended unconverged/with
  bookkeeping gaps, is a failed experiment regardless of how fast it
  ran.

Drain/convergence wall-time ceilings are tolerance-scaled like every
other latency surface.
"""

from __future__ import annotations

from corrosion_tpu.sim import benchlib, telemetry

HOSTCHAOS_DIMS = ("platform", "scenario")


def emit_hostchaos_report(report: dict) -> dict:
    """The host-chaos emit site: assert self-description (base
    provenance + ``scenario``) and return the report unchanged."""
    return telemetry.check_bench_invariants(
        report, extra_provenance=("scenario",)
    )


def hostchaos_context(nodes: int, *fingerprint_parts) -> dict:
    return {
        **benchlib.bench_context(
            "host_chaos_smoke", nodes, *fingerprint_parts
        ),
        "scenario": "host_chaos_smoke",
        "nodes": nodes,
    }


_get = benchlib.get_path


def check_hostchaos_budget(
    measured: dict, budget: dict
) -> tuple[bool, list[str]]:
    """Gate a host-chaos smoke report against the ``hostchaos`` budget
    entry. Returns ``(ok, breaches)``."""
    tol = float(budget.get("tolerance", benchlib.DEFAULT_TOLERANCE))
    breaches: list[str] = []
    for dim in HOSTCHAOS_DIMS:
        if dim in budget and measured.get(dim) != budget[dim]:
            breaches.append(
                f"{dim}: measured at {measured.get(dim)!r} but the budget "
                f"was refreshed at {budget[dim]!r} — rerun with --update"
            )
    scenarios = budget.get("scenarios", [])
    blocks = measured.get("scenarios", {})
    missing = [s for s in scenarios if s not in blocks]
    if missing:
        breaches.append(
            f"scenarios missing from measurement: {missing} — a silently "
            f"vanished scenario is how regressions hide"
        )
    for path, limit in budget.get("ceilings_s", {}).items():
        got = _get(measured, path)
        if got is None:
            breaches.append(f"{path}: missing from measurement")
        elif float(got) > float(limit) * tol:
            breaches.append(
                f"{path}: {float(got):.1f} s > budget "
                f"{float(limit):.1f} s x{tol}"
            )
    viol_max = int(budget.get("oracle_violations_max", 0))
    total_viol = sum(
        int(_get(blk, "oracle.violations") or 0) for blk in blocks.values()
    )
    if total_viol > viol_max:
        breaches.append(
            f"oracle violations: {total_viol} > {viol_max} — exactly-once "
            f"delivery or change-id monotonicity broke under chaos"
        )
    if budget.get("require_machinery_fired", True):
        for name, blk in blocks.items():
            if not blk.get("machinery_ok", False):
                breaches.append(
                    f"{name}: required machinery never fired "
                    f"(required={blk.get('machinery_required')}, "
                    f"counters={blk.get('machinery')}) — the scenario "
                    f"did not actually stress its defenses"
                )
    if budget.get("require_converged", True):
        for name, blk in blocks.items():
            if not (
                blk.get("converged")
                and blk.get("bookkeeping_contiguous")
                and blk.get("ok")
            ):
                breaches.append(
                    f"{name}: post-heal invariants failed: "
                    f"{blk.get('failures')}"
                )
    return not breaches, breaches
