"""The host chaos runner: netem plan + loadgen storm + oracle + post-heal
invariants + machinery-fired assertions.

One :func:`run_scenario` call is a complete experiment:

1. launch a real loopback cluster (agent/testing) with the scenario's
   ``corro-host-fault-plan/1`` installed as a NetemShim on every agent's
   transport (per-agent link names ``n0..n{k}``);
2. attach oracle-checked NDJSON subscriptions (loadgen.SubscriptionPump
   with auto-reconnect — durable-sub resume is part of the contract);
3. arm the fault windows and drive an open-loop write storm through the
   HTTP API, round-robin over the agents that are currently alive;
4. optionally SIGKILL one agent mid-storm (Agent.abort — no graceful
   leave, no final flushes) and relaunch it on the same data_dir/ports;
5. wait for the plan horizon, drain the fan-out, and check the post-heal
   invariants: ZERO fan-out-oracle violations, identical CRDT table
   state on every agent (and ⊇ every acked commit), identical per-actor
   bookkeeping heads with no version gaps or dangling partials;
6. assert the defensive machinery the scenario was built to force
   actually fired (``require_fired``): a chaos scenario that passes with
   its defenses idle is a test-harness failure, not a success — the
   report says so explicitly.

The report embeds the plan, per-agent impairment traces + fingerprints,
and the machinery counters, so ``hostchaos replay`` can mechanically
verify that the same seed reproduces the identical fault schedule.
"""

from __future__ import annotations

import asyncio
import os
import time
from dataclasses import dataclass, field

from corrosion_tpu.agent.netem import HostFaultPlan, replay_schedule
from corrosion_tpu.agent.testing import (
    hard_kill,
    launch_test_cluster,
    relaunch_test_agent,
    stop_cluster,
)
from corrosion_tpu.core.bookkeeping import generate_sync
from corrosion_tpu.loadgen.harness import (
    LoadHarness,
    SubscriptionPump,
    stop_pumps,
)
from corrosion_tpu.loadgen.oracle import FanoutOracle
from corrosion_tpu.loadgen.schedule import Arrival, open_loop

# Harness key -> metric series (summed across every agent life,
# including the pre-kill snapshot of a crashed agent's registry).
MACHINERY = {
    "stall_aborts": "corro_sync_stall_aborts_total",
    "chunk_halvings": "corro_sync_chunk_halvings_total",
    "breaker_trips": "corro_peer_breaker_trips_total",
    "breaker_recoveries": "corro_peer_breaker_recoveries_total",
    "backoff_retries": "corro_peer_backoff_retries_total",
}

# Trace entries embedded per agent in the report (fingerprints cover the
# FULL trace; the prefix keeps report JSONs reviewable).
REPORT_TRACE_CAP = 300


@dataclass(frozen=True)
class KillSpec:
    """SIGKILL agent ``agent`` at ``t_kill_s`` (storm-relative) and
    relaunch it on the same data_dir/ports at ``t_restart_s``."""

    agent: int
    t_kill_s: float
    t_restart_s: float


@dataclass(frozen=True)
class HostScenario:
    name: str
    plan: HostFaultPlan
    n_agents: int = 3
    writes: int = 40
    write_rate: float = 8.0
    subs: int = 9
    sub_groups: int = 3
    subs_on: int = 0
    kill: KillSpec | None = None
    require_fired: tuple = ()  # MACHINERY keys that MUST be >= 1
    agent_cfg: dict = field(default_factory=dict)
    drain_timeout_s: float = 45.0
    notes: str = ""

    def summary(self) -> str:
        kinds = ",".join(sorted({f.kind for f in self.plan.faults})) or "none"
        kill = (
            f"; kill n{self.kill.agent}@{self.kill.t_kill_s}s"
            f"->restart@{self.kill.t_restart_s}s" if self.kill else ""
        )
        req = ",".join(self.require_fired) or "-"
        return (
            f"{self.n_agents} agents, {self.writes} writes @ "
            f"{self.write_rate:g}/s, faults[{kinds}]{kill}; must fire: {req}"
        )


def _counter_total(snapshots: list[dict], series: str) -> float:
    """Sum a (possibly labeled) counter series across metric snapshots."""
    total = 0.0
    for snap in snapshots:
        for key, v in snap.items():
            if key == series or key.startswith(series + "{"):
                total += v
    return total


def _wire_netem(agents, arm_at: float | None = None) -> None:
    """Resolve every peer's gossip addr to its plan-space name on every
    shim, then start the fault windows (shared origin: a restarted
    agent's fresh shim arms at the ORIGINAL origin so its windows line
    up with the rest of the cluster)."""
    for i, ta in enumerate(agents):
        shim = ta.agent.netem
        if shim is None:
            continue
        for j, tb in enumerate(agents):
            if j != i and tb is not None and tb.gossip_addr is not None:
                shim.register_peer(tb.gossip_addr, f"n{j}")
        if arm_at is not None:
            shim.arm(at=arm_at)


async def _rows_of(ta) -> dict:
    _cols, rows = await ta.client.query(
        "SELECT id, text FROM tests ORDER BY id"
    )
    return {r[0]: r[1] for r in rows}


def _bookkeeping_check(agents) -> tuple[bool, list[str], dict]:
    """Post-heal bookkeeping contiguity + cross-agent head agreement."""
    failures: list[str] = []
    heads: dict[str, dict[int, int]] = {}
    for i, ta in enumerate(agents):
        st = generate_sync(ta.agent.bookie, ta.agent.actor_id)
        gaps = {a: rs for a, rs in st.need.items() if rs}
        partials = {a: p for a, p in st.partial_need.items() if p}
        if gaps:
            failures.append(f"n{i}: version gaps remain: {gaps}")
        if partials:
            failures.append(f"n{i}: dangling partials: {partials}")
        for actor, head in st.heads.items():
            heads.setdefault(actor, {})[i] = head
    for actor, per_agent in heads.items():
        if len(per_agent) != len(agents):
            missing = [i for i in range(len(agents)) if i not in per_agent]
            failures.append(
                f"actor {actor[:8]}: unknown to agents {missing}"
            )
        elif len(set(per_agent.values())) != 1:
            failures.append(
                f"actor {actor[:8]}: heads disagree: {per_agent}"
            )
    summary = {
        a[:8]: sorted(set(pa.values()))[-1] for a, pa in heads.items()
    }
    return not failures, failures, summary


async def run_scenario(
    spec: HostScenario,
    data_dir: str,
    seed: int = 0,
    progress=None,
    series_dir: str | None = None,
    series_interval: float = 0.25,
    endurance_kw: dict | None = None,
    sub_costs: bool = False,
) -> dict:
    """Run one scenario end to end; returns the report dict (``ok`` is
    the overall verdict — oracle, convergence, bookkeeping, machinery).

    ``series_dir`` arms the ENDURANCE plane: every agent streams one
    whole-registry snapshot per ``series_interval`` to
    ``<series_dir>/n<i>.series.jsonl`` (obs/series.py; a killed+
    relaunched agent reopens its series ``mode="a"`` so the restart
    discontinuity lands in ONE record), and the report gains an
    ``endurance`` block with one corro-endurance/1 verdict per agent
    (obs/endurance.py detectors, tuned via ``endurance_kw``).

    ``sub_costs`` arms the serving query-cost plane on every agent
    (``AgentConfig.sub_costs``): the report gains a ``sub_costs`` block
    with the subs-hosting agent's ``corro-sub-cost/1`` ledger, and crash
    scenarios additionally prove ledger ADOPTION — the relaunched agent
    re-reads its persisted per-subscription counters from the sub dbs
    (the same restart-survival contract as the series recorder), so a
    kill cannot silently zero the cost attribution."""

    def note(msg: str) -> None:
        if progress is not None:
            progress.write(f"[hostchaos {spec.name}] {msg}\n")
            progress.flush()

    loop = asyncio.get_running_loop()
    plan_obj = spec.plan.to_json_obj()
    netem_on = not spec.plan.empty
    cluster_kw: dict = dict(spec.agent_cfg)
    cfg_for = None
    if netem_on or series_dir is not None or sub_costs:
        def cfg_for(i, _plan=plan_obj, _seed=seed):
            cfg: dict = {}
            if netem_on:
                cfg.update({
                    "netem_plan": _plan, "netem_seed": _seed,
                    "netem_node": f"n{i}",
                })
            if series_dir is not None:
                cfg.update({
                    "metric_series_path": os.path.join(
                        series_dir, f"n{i}.series.jsonl"
                    ),
                    "runtime_metrics_interval": series_interval,
                })
            if sub_costs:
                cfg["sub_costs"] = True
            return cfg
    note(f"launching {spec.n_agents} agents (netem={netem_on}, seed={seed})")
    agents = await launch_test_cluster(
        data_dir, spec.n_agents, wait_membership=True,
        membership_timeout=30.0, cfg_for=cfg_for, **cluster_kw,
    )
    harness = LoadHarness()
    oracle = FanoutOracle(registry=harness.registry)
    pumps: list[SubscriptionPump] = []
    pre_kill_snapshots: list[dict] = []
    failures: list[str] = []
    kill_report: dict = {}
    live: set[int] = set(range(spec.n_agents))
    try:
        # Subscriptions on the designated agent (the kill target in
        # crash scenarios — durable-sub resume is under test).
        note(f"attaching {spec.subs} subscriptions on n{spec.subs_on}")
        sub_client = agents[spec.subs_on].client
        for i in range(spec.subs):
            g = i % spec.sub_groups
            pump = SubscriptionPump(
                sub_client,
                f"SELECT id, text FROM tests WHERE id % {spec.sub_groups}"
                f" = {g}",
                oracle, group=g, label=f"sub{i}",
                reconnect_retries=150, reconnect_delay_s=0.2,
            )
            pumps.append(pump)
        await asyncio.gather(*(p.start() for p in pumps))

        # Arm the fault windows: storm-relative time starts NOW.
        t_arm = time.monotonic()
        _wire_netem(agents, arm_at=t_arm)
        note("armed fault windows; storm starts")

        next_key = iter(range(10**9))

        async def fire_write(a: Arrival):
            k = next(next_key)
            payload = f"chaos-w{k}"
            # Round-robin over agents currently alive: a crashed agent
            # takes no writes while down (its API is gone), exactly like
            # a load balancer pulling a dead backend.
            order = [
                (k + off) % spec.n_agents for off in range(spec.n_agents)
            ]
            idx = next((i for i in order if i in live), None)
            if idx is None:
                return
            ta = agents[idx]

            async def go():
                await ta.client.execute(
                    [["INSERT INTO tests (id, text) VALUES (?, ?)",
                      [k, payload]]]
                )
                oracle.commit(
                    k, (payload,), loop.time(), group=k % spec.sub_groups
                )

            await harness.timed("transactions", a, go, deadline_s=30.0)

        async def kill_task():
            ks = spec.kill
            if ks is None:
                return
            await asyncio.sleep(max(0.0, ks.t_kill_s))
            victim = agents[ks.agent]
            note(f"hard-killing n{ks.agent} (SIGKILL semantics)")
            live.discard(ks.agent)
            t0 = time.monotonic()
            pre_kill_snapshots.append(victim.agent.metrics.snapshot())
            if sub_costs and victim.agent.subs is not None:
                kill_report["cost_pre_kill"] = (
                    victim.agent.subs.cost_snapshot()["totals"]
                )
            await hard_kill(victim)
            await asyncio.sleep(
                max(0.0, ks.t_restart_s - ks.t_kill_s
                    - (time.monotonic() - t0))
            )
            boot = [
                agents[i].gossip_addr
                for i in sorted(live) if i != ks.agent
            ][:2]
            note(f"relaunching n{ks.agent} on its data_dir/ports")
            agents[ks.agent] = await relaunch_test_agent(
                victim, bootstrap=boot
            )
            # The fresh shim shares the ORIGINAL window origin.
            _wire_netem(agents, arm_at=None)
            shim = agents[ks.agent].agent.netem
            if shim is not None:
                shim.arm(at=t_arm)
            if sub_costs and agents[ks.agent].agent.subs is not None:
                # Snapshot BEFORE the agent rejoins the write rotation:
                # nonzero counters here can only have come from the
                # persisted ledger (modulo gossip catch-up), proving the
                # relaunch adopted the previous life's attribution.
                kill_report["cost_adopted"] = (
                    agents[ks.agent].agent.subs.cost_snapshot()["totals"]
                )
            live.add(ks.agent)
            kill_report.update({
                "agent": ks.agent,
                "killed_at_s": round(ks.t_kill_s, 2),
                "restarted_after_s": round(time.monotonic() - t0, 2),
            })

        await asyncio.gather(
            harness.run_arrivals(
                open_loop(spec.write_rate, spec.writes), fire_write
            ),
            kill_task(),
        )
        note("storm done")

        # Let every scheduled fault window clear before judging heal.
        horizon = spec.plan.horizon_s()
        if horizon != float("inf"):
            remaining = horizon - (time.monotonic() - t_arm)
            if remaining > 0:
                note(f"waiting {remaining:.1f}s for fault windows to clear")
                await asyncio.sleep(remaining)

        # Drain: every acked commit must reach every obliged stream.
        t_drain = time.monotonic()
        deadline = t_drain + spec.drain_timeout_s
        while oracle.pending(limit=1) and time.monotonic() < deadline:
            await asyncio.sleep(0.1)
        drain_s = time.monotonic() - t_drain
        note(f"fan-out drained in {drain_s:.1f}s "
             f"(pending={oracle.pending(limit=50)})")

        # Post-heal CRDT agreement: identical table state everywhere,
        # covering every acked commit (the host plane's serial-merge
        # oracle: the acked-commit set IS the ground truth).
        expected = {k: p[0] for k, p in oracle.committed().items()}
        t_conv = time.monotonic()
        agree = False
        rows_by_agent: list[dict] = []
        while time.monotonic() < deadline + 10.0:
            rows_by_agent = [await _rows_of(ta) for ta in agents]
            covered = all(
                all(r.get(k) == v for k, v in expected.items())
                for r in rows_by_agent
            )
            identical = all(r == rows_by_agent[0] for r in rows_by_agent)
            if covered and identical:
                agree = True
                break
            await asyncio.sleep(0.2)
        convergence_s = time.monotonic() - t_conv
        if not agree:
            counts = [len(r) for r in rows_by_agent]
            failures.append(
                f"CRDT state disagrees post-heal: row counts {counts}, "
                f"expected >= {len(expected)} identical everywhere"
            )

        book_ok, book_fail, heads = _bookkeeping_check(agents)
        failures.extend(book_fail)

        verdict = oracle.finish()
        if verdict["violations"]:
            failures.append(
                f"fan-out oracle: {verdict['violations']} violations: "
                f"{verdict['violation_examples'][:3]}"
            )
        if verdict["commits"] == 0 or verdict["delivered_changes"] == 0:
            failures.append(
                "vacuous run: no commit/delivery traffic — the storm "
                "never exercised anything"
            )

        snapshots = pre_kill_snapshots + [
            ta.agent.metrics.snapshot() for ta in agents
        ]
        machinery = {
            key: _counter_total(snapshots, series)
            for key, series in MACHINERY.items()
        }
        unfired = [
            key for key in spec.require_fired if machinery.get(key, 0) < 1
        ]
        machinery_ok = not unfired
        if unfired:
            # The scenario exists to FORCE these defenses; green
            # invariants with idle defenses mean the harness failed to
            # apply stress, not that the system is robust.
            failures.append(
                f"test-harness failure: scenario was built to force "
                f"{list(spec.require_fired)} but {unfired} never fired "
                f"(machinery={machinery})"
            )

        sub_cost_block = None
        if sub_costs:
            mgr = agents[spec.subs_on].agent.subs
            ledger = mgr.cost_snapshot() if mgr is not None else None
            sub_cost_block = {"enabled": True, "ledger": ledger}
            pre = kill_report.get("cost_pre_kill")
            adopted = kill_report.get("cost_adopted")
            if (
                spec.kill is not None and spec.kill.agent == spec.subs_on
                and pre is not None and pre.get("fanout_events", 0) > 0
                and adopted is not None
                and adopted.get("fanout_events", 0) == 0
                and adopted.get("candidate_evals", 0)
                + adopted.get("fallback_evals", 0) == 0
            ):
                # The previous life demonstrably published (and
                # publishing persists the cost row in the same sub-db
                # transaction as the events), yet the relaunched agent
                # came back with an all-zero ledger: adoption broke.
                failures.append(
                    f"cost-ledger adoption failed: n{spec.kill.agent} "
                    f"relaunched with an empty ledger despite "
                    f"{pre['fanout_events']} pre-kill fan-out events"
                )

        endurance_block = None
        if series_dir is not None:
            # Judge each agent's recorded series (flush-per-line: the
            # record is complete up to the last tick even though the
            # recorders are still open). Replay + detectors live in the
            # jax-free obs modules.
            from corrosion_tpu.obs.endurance import build_report
            from corrosion_tpu.obs.series import replay_series

            per_agent_end: dict[str, dict] = {}
            for i in range(spec.n_agents):
                path = os.path.join(series_dir, f"n{i}.series.jsonl")
                try:
                    samples = replay_series(path)["samples"]
                except OSError:
                    samples = []
                per_agent_end[f"n{i}"] = build_report(
                    samples, label=f"{spec.name}:n{i}",
                    **(endurance_kw or {}),
                )
            endurance_block = {
                "dir": series_dir,
                "interval_s": series_interval,
                "agents": per_agent_end,
            }

        netem_block = {}
        if netem_on:
            per_agent = {}
            for i, ta in enumerate(agents):
                shim = ta.agent.netem
                if shim is None:
                    continue
                per_agent[f"n{i}"] = {
                    "stats": dict(shim.stats),
                    "trace_fingerprint": shim.fingerprint(),
                    "trace_len": len(shim.trace),
                    "trace_overflow": shim.trace_overflow,
                    "trace": shim.trace[:REPORT_TRACE_CAP],
                }
            netem_block = {"seed": seed, "agents": per_agent}

        return {
            "scenario": spec.name,
            "seed": seed,
            "agents": spec.n_agents,
            "plan": plan_obj,
            "writes_requested": spec.writes,
            "routes": {"transactions": harness.route_report("transactions")},
            "oracle": verdict,
            "kill": kill_report or None,
            "drain_s": round(drain_s, 2),
            "convergence_s": round(convergence_s, 2),
            "converged": agree,
            "bookkeeping_contiguous": book_ok,
            "heads": heads,
            "machinery": machinery,
            "machinery_required": list(spec.require_fired),
            "machinery_ok": machinery_ok,
            "endurance": endurance_block,
            "sub_costs": sub_cost_block,
            "netem": netem_block,
            "ok": not failures,
            "failures": failures,
        }
    finally:
        await stop_pumps(pumps)
        await stop_cluster([ta for ta in agents if ta is not None])


def verify_schedule_determinism(report: dict) -> tuple[bool, list[str]]:
    """Replay the fault schedule recorded in a scenario report from its
    (plan, seed) alone: every embedded trace entry must reproduce
    exactly (``hostchaos replay``; docs/CHAOS.md "Host plane")."""
    if "plan" not in report:
        return False, [
            "not a scenario report (no `plan`): pass a `hostchaos run` "
            "report, not the smoke aggregate"
        ]
    netem = report.get("netem") or {}
    plan = HostFaultPlan.from_json(report["plan"])
    seed = int(netem.get("seed", report.get("seed", 0)))
    problems: list[str] = []
    agents = netem.get("agents") or {}
    if not agents:
        if plan.empty:
            # A netem-free scenario (e.g. kill_restart) legitimately
            # records zero decisions: nothing to replay, vacuously green.
            return True, []
        return False, [
            "plan has fault components but the report carries no netem "
            "traces to replay"
        ]
    for name, blk in agents.items():
        ok, mismatches = replay_schedule(plan, seed, name, blk["trace"])
        if not ok:
            problems.extend(f"{name}: {m}" for m in mismatches[:5])
    return not problems, problems
