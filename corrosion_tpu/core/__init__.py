"""Shared pure logic used by both the host agent and the JAX sim engine.

Mirrors the reference's corro-base-types, corro-api-types and the pure parts
of corro-types (SURVEY.md §2): id newtypes, hybrid logical clock, interval
sets/maps, value types, change chunking, bookkeeping, sync-need computation,
wire messages and codec.
"""
