"""Identity and version newtypes.

Mirrors corro-base-types/src/lib.rs (Version/CrsqlDbVersion/CrsqlSeq u64
newtypes) and corro-types/src/actor.rs (ActorId = 16-byte site id; Actor =
id + gossip addr + join timestamp + cluster id).

In the TPU sim, an ActorId maps to a dense node index (int32); the host agent
uses the full 16-byte id on the wire and as the CRR site_id.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field

# u64 newtypes — plain ints with semantic aliases. Version is the per-actor
# logical version (one per committed local transaction); DbVersion is the CRR
# database version assigned by the storage layer; Seq orders the rows of one
# changeset so large transactions can stream in chunks.
Version = int
DbVersion = int
Seq = int


@dataclass(frozen=True, order=True)
class ActorId:
    """16-byte actor identity (== the CRR site_id), like actor.rs:26."""

    bytes: bytes = field(default=b"\x00" * 16)

    def __post_init__(self) -> None:
        if len(self.bytes) != 16:
            raise ValueError(f"ActorId must be 16 bytes, got {len(self.bytes)}")

    @classmethod
    def random(cls) -> "ActorId":
        return cls(uuid.uuid4().bytes)

    @classmethod
    def from_hex(cls, s: str) -> "ActorId":
        return cls(uuid.UUID(s.replace("-", "")).bytes)

    @property
    def hex(self) -> str:
        return self.bytes.hex()

    @property
    def uuid(self) -> uuid.UUID:
        return uuid.UUID(bytes=self.bytes)

    def to_node_index(self, n_nodes: int) -> int:
        """Stable dense-index hash for sim-side sharding."""
        return int.from_bytes(self.bytes[:8], "big") % n_nodes

    def __str__(self) -> str:
        return str(self.uuid)

    def __repr__(self) -> str:
        return f"ActorId({self.uuid})"


@dataclass(frozen=True)
class Actor:
    """Cluster identity carried in SWIM messages (actor.rs:134-194).

    ``bump`` mirrors the renew counter: when a node is declared down it renews
    its identity (same id/addr, bumped counter) and auto-rejoins.
    """

    id: ActorId
    addr: tuple[str, int]  # (host, port) of the gossip endpoint
    ts: int = 0  # HLC timestamp at join/renew
    bump: int = 0

    def renew(self, ts: int) -> "Actor":
        return Actor(self.id, self.addr, ts, self.bump + 1)

    def same_node(self, other: "Actor") -> bool:
        return self.id == other.id

    def wins_over(self, other: "Actor") -> bool:
        """Higher bump (then ts) replaces an older identity for the same id."""
        return (self.bump, self.ts) > (other.bump, other.ts)
