"""Version bookkeeping + sync-need computation (host side).

Faithful re-implementation of the reference's replication bookkeeping:

- ``KnownDbVersion`` variants and ``BookedVersions`` with its
  cleared/current/partials tri-state and the ``sync_need`` gap set
  (reference corro-types/src/agent.rs:580-591, 945-1052; ``insert_many``
  semantics at agent.rs:1009-1047).
- ``SyncState`` — heads / need / partial_need — and
  ``compute_available_needs`` (the version-vector diff that drives every
  anti-entropy session; reference corro-types/src/sync.rs:77-246), plus
  ``generate_sync`` (sync.rs:276-323).

Tested against translations of the reference's own unit vectors
(sync.rs:376-491) in tests/test_bookkeeping.py. The JAX sync plane models
the same math batched (ops/gossip.py sync_round; ops/chunks.py partial
needs); the host agent uses this exact version.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable

from .intervals import RangeSet


@dataclass(frozen=True)
class Current:
    """A fully-applied version (agent.rs:897-905)."""

    db_version: int
    last_seq: int
    ts: int


@dataclass
class Partial:
    """A partially-buffered version: seq coverage + the final seq
    (agent.rs:907-914)."""

    seqs: RangeSet
    last_seq: int
    ts: int

    def is_complete(self) -> bool:
        return self.seqs.contains_range(0, self.last_seq)

    def gaps(self) -> list[tuple[int, int]]:
        return list(self.seqs.gaps(0, self.last_seq))


class Cleared:
    """Marker for compacted/emptied versions (KnownDbVersion::Cleared)."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "Cleared"


CLEARED = Cleared()
KnownDbVersion = Current | Partial | Cleared


class BookedVersions:
    """Per-actor version -> {Cleared, Current, Partial} map with gap
    tracking (reference agent.rs:945-1052)."""

    __slots__ = ("cleared", "current", "partials", "_sync_need", "_last")

    def __init__(self) -> None:
        self.cleared = RangeSet()
        self.current: dict[int, Current] = {}
        self.partials: dict[int, Partial] = {}
        self._sync_need = RangeSet()
        self._last: int | None = None

    # -- queries (agent.rs:958-1007) ---------------------------------------

    def contains_version(self, version: int) -> bool:
        return (
            self.cleared.contains(version)
            or version in self.current
            or version in self.partials
        )

    def get(self, version: int) -> KnownDbVersion | None:
        if self.cleared.contains(version):
            return CLEARED
        if version in self.current:
            return self.current[version]
        if version in self.partials:
            return self.partials[version]
        return None

    def contains(self, version: int, seqs: tuple[int, int] | None = None) -> bool:
        if not self.contains_version(version):
            return False
        if seqs is None:
            return True
        known = self.get(version)
        if isinstance(known, Partial):
            return known.seqs.contains_range(seqs[0], seqs[1])
        return True  # Cleared / Current hold every seq

    def contains_all(
        self, versions: tuple[int, int], seqs: tuple[int, int] | None = None
    ) -> bool:
        return all(
            self.contains(v, seqs) for v in range(versions[0], versions[1] + 1)
        )

    def last(self) -> int | None:
        return self._last

    def current_versions(self) -> dict[int, int]:
        """db_version -> version (agent.rs:994-999)."""
        return {c.db_version: v for v, c in self.current.items()}

    # -- mutation (agent.rs:1005-1047) -------------------------------------

    def insert(self, version: int, known: KnownDbVersion) -> None:
        self.insert_many(version, version, known)

    def insert_many(self, start: int, end: int, known: KnownDbVersion) -> None:
        """Record [start, end] as ``known``; track gaps below ``start`` as
        sync need — exactly insert_many (agent.rs:1009-1047): Partial/Current
        apply to ``start`` only (single-version callers), Cleared applies to
        the whole range."""
        if isinstance(known, Partial):
            self.partials[start] = known
        elif isinstance(known, Current):
            self.partials.pop(start, None)
            self.current[start] = known
        else:  # Cleared
            for v in range(start, end + 1):
                self.partials.pop(v, None)
                self.current.pop(v, None)
            self.cleared.insert(start, end)

        old_last = self._last if self._last is not None else 0
        self._last = max(end, old_last)
        if old_last < start:
            # Versions we skipped over are needed (agent.rs:1038-1043).
            self._sync_need.insert(old_last + 1, start)
        self._sync_need.remove(start, end)

    def sync_need(self) -> RangeSet:
        return self._sync_need


@dataclass
class SyncState:
    """heads / need / partial_need per actor (sync.rs:77-83)."""

    actor_id: str = ""
    heads: dict[str, int] = field(default_factory=dict)
    need: dict[str, list[tuple[int, int]]] = field(default_factory=dict)
    partial_need: dict[str, dict[int, list[tuple[int, int]]]] = field(
        default_factory=dict
    )

    def need_len(self) -> int:
        """sync.rs:86-105 (partial ranges are counted as chunks / 50)."""
        full = sum(
            e - s + 1 for ranges in self.need.values() for s, e in ranges
        )
        partial_seqs = sum(
            e - s + 1
            for partials in self.partial_need.values()
            for ranges in partials.values()
            for s, e in ranges
        )
        return full + partial_seqs // 50

    def need_len_for_actor(self, actor_id: str) -> int:
        """sync.rs:107-121."""
        return sum(
            e - s + 1 for s, e in self.need.get(actor_id, [])
        ) + len(self.partial_need.get(actor_id, {}))

    def compute_available_needs(
        self, other: "SyncState"
    ) -> dict[str, list["SyncNeed"]]:
        """What ``other`` can serve us: the version-vector diff at the heart
        of every sync session (sync.rs:123-246)."""
        needs: dict[str, list[SyncNeed]] = {}

        for actor_id, head in other.heads.items():
            if actor_id == self.actor_id or head == 0:
                continue

            # Versions `other` FULLY has: [1, head] minus its needs and its
            # partials (sync.rs:139-161).
            other_haves = RangeSet([(1, head)])
            for s, e in other.need.get(actor_id, []):
                other_haves.remove(s, e)
            for v in other.partial_need.get(actor_id, {}):
                other_haves.remove(v, v)

            # Full needs of ours they can serve (sync.rs:163-174).
            for rs, re_ in self.need.get(actor_id, []):
                for hs, he in other_haves:
                    if hs > re_ or he < rs:
                        continue
                    needs.setdefault(actor_id, []).append(
                        FullNeed(max(rs, hs), min(re_, he))
                    )

            # Partial needs (sync.rs:176-228).
            for v, seqs in self.partial_need.get(actor_id, {}).items():
                if other_haves.contains(v):
                    needs.setdefault(actor_id, []).append(
                        PartialNeed(v, list(seqs))
                    )
                else:
                    other_seqs = other.partial_need.get(actor_id, {}).get(v)
                    if other_seqs is None:
                        continue
                    max_other = max((e for _, e in other_seqs), default=None)
                    max_ours = max((e for _, e in seqs), default=None)
                    ends = [x for x in (max_other, max_ours) if x is not None]
                    if not ends:
                        continue
                    end = max(ends)
                    # Seqs `other` has within its partial (sync.rs:196-204).
                    other_seq_haves = RangeSet([(0, end)])
                    for s, e in other_seqs:
                        other_seq_haves.remove(s, e)
                    overlap = [
                        (max(rs, hs), min(re_, he))
                        for rs, re_ in seqs
                        for hs, he in other_seq_haves
                        if hs <= re_ and he >= rs
                    ]
                    if overlap:
                        needs.setdefault(actor_id, []).append(
                            PartialNeed(v, overlap)
                        )

            # Head gap (sync.rs:230-243).
            our_head = self.heads.get(actor_id)
            if our_head is None:
                needs.setdefault(actor_id, []).append(FullNeed(1, head))
            elif head > our_head:
                needs.setdefault(actor_id, []).append(
                    FullNeed(our_head + 1, head)
                )

        return needs


@dataclass(frozen=True)
class FullNeed:
    """SyncNeedV1::Full (sync.rs:248-251)."""

    start: int
    end: int

    def count(self) -> int:
        return self.end - self.start + 1


@dataclass(frozen=True)
class PartialNeed:
    """SyncNeedV1::Partial (sync.rs:252-257)."""

    version: int
    seqs: list[tuple[int, int]]

    def count(self) -> int:
        return 1


SyncNeed = FullNeed | PartialNeed


class Bookie:
    """actor_id -> BookedVersions (reference agent.rs:1129-1170, sans the
    counted-lock wrapper — the host agent is single-threaded per node)."""

    def __init__(self) -> None:
        self._by_actor: dict[str, BookedVersions] = {}

    def for_actor(self, actor_id: str) -> BookedVersions:
        return self._by_actor.setdefault(actor_id, BookedVersions())

    def get(self, actor_id: str) -> BookedVersions | None:
        return self._by_actor.get(actor_id)

    def items(self) -> Iterable[tuple[str, BookedVersions]]:
        return self._by_actor.items()


def generate_sync(bookie: Bookie, actor_id: str) -> SyncState:
    """Build our SyncState to open a session (sync.rs:276-323)."""
    state = SyncState(actor_id=actor_id)
    for other_id, booked in bookie.items():
        last = booked.last()
        if last is None:
            continue
        need = list(booked.sync_need())
        if need:
            state.need[other_id] = need
        for v, partial in booked.partials.items():
            state.partial_need.setdefault(other_id, {})[v] = partial.gaps()
        state.heads[other_id] = last
    return state
