"""Changeset chunking.

Mirrors corro-types/src/change.rs (`ChunkedChanges` :8-114): stream rows of a
(possibly huge) transaction into chunks of at most ``max_bytes`` estimated
wire bytes, each tagged with the inclusive seq range it covers, so a single
10k-row transaction can be broadcast/synced incrementally and reassembled with
gap tracking on the receiving side.
"""

from __future__ import annotations

from typing import Iterable, Iterator

from .values import Change

MAX_CHANGES_BYTE_SIZE = 8 * 1024  # change.rs:116


def chunk_changes(
    rows: Iterable[Change],
    last_seq: int,
    max_bytes: int = MAX_CHANGES_BYTE_SIZE,
) -> Iterator[tuple[list[Change], tuple[int, int]]]:
    """Yield (changes, (seq_start, seq_end)) chunks.

    Seq ranges tile [0, last_seq] contiguously even when rows skip seqs, and
    the final chunk always extends to ``last_seq`` — matching ChunkedChanges:
    the receiver tracks which seq ranges it holds, so emitted ranges must
    cover the whole transaction without holes.
    """
    chunk: list[Change] = []
    chunk_start = 0
    size = 0
    for row in rows:
        chunk.append(row)
        size += row.estimated_byte_size()
        if size >= max_bytes:
            yield chunk, (chunk_start, row.seq)
            chunk_start = row.seq + 1
            chunk = []
            size = 0
    if chunk or chunk_start <= last_seq:
        yield chunk, (chunk_start, last_seq)


def max_seq(rows: list[Change], default: int = 0) -> int:
    return max((r.seq for r in rows), default=default)


class AdaptiveChunker:
    """Adaptive sync chunk sizing (peer.rs:352-355, 638-653): the server
    halves its chunk byte target whenever a send takes longer than the
    threshold (500 ms in the reference), floored at 1 KiB — a slow or
    congested peer gets smaller messages instead of head-of-line blocking.
    """

    def __init__(
        self,
        max_bytes: int = MAX_CHANGES_BYTE_SIZE,
        min_bytes: int = 1024,
        threshold_s: float = 0.5,
    ) -> None:
        self.max_bytes = max_bytes
        self.min_bytes = min_bytes
        self.threshold_s = threshold_s
        self.halvings = 0

    def record(self, send_seconds: float) -> bool:
        """Feed one send duration. Returns True when the chunk target
        actually halved (already-at-floor slow sends don't count — the
        defense has no smaller step left to take), so the caller can
        surface halvings as a counter."""
        if send_seconds > self.threshold_s:
            new = max(self.min_bytes, self.max_bytes // 2)
            if new < self.max_bytes:
                self.max_bytes = new
                self.halvings += 1
                return True
        return False
