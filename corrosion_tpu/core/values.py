"""API value types: SQL values, changes, statements, query events.

Mirrors corro-api-types/src/lib.rs: `Change` (:210-238), `Statement`
(:168-195), `ExecResponse`/`ExecResult` (:197-208), `QueryEvent` (:25-62),
`SqliteValue` (:255-530), and the column packing used for primary keys
(corro-types/src/pubsub.rs:2115-2283).

SqliteValue is represented natively: None | int | float | str | bytes.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, Iterable, Union

SqliteValue = Union[None, int, float, str, bytes]

# type tags for pack_columns — ordered like SQLite's cross-type ordering
# (NULL < numeric < text < blob), so tag comparison gives type precedence.
T_NULL, T_INT, T_REAL, T_TEXT, T_BLOB = 0, 1, 2, 3, 4


def _tag(v: SqliteValue) -> int:
    if v is None:
        return T_NULL
    if isinstance(v, bool):
        return T_INT
    if isinstance(v, int):
        return T_INT
    if isinstance(v, float):
        return T_REAL
    if isinstance(v, str):
        return T_TEXT
    if isinstance(v, bytes):
        return T_BLOB
    raise TypeError(f"unsupported SQL value type: {type(v)}")


def _write_varint(out: bytearray, n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


class MalformedBlobError(ValueError):
    """Raised when a packed-column blob is truncated or corrupt."""


def _read_varint(buf: bytes, i: int) -> tuple[int, int]:
    shift = 0
    n = 0
    while True:
        if i >= len(buf):
            raise MalformedBlobError(f"truncated varint at offset {i}")
        if shift > 63:
            raise MalformedBlobError(f"varint overflow at offset {i}")
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def _py_pack_columns(values: Iterable[SqliteValue]) -> bytes:
    out = bytearray()
    for v in values:
        tag = _tag(v)
        out.append(tag)
        if tag == T_NULL:
            continue
        if tag == T_INT:
            n = int(v)
            if not -(1 << 63) <= n < (1 << 63):
                raise ValueError(f"integer out of SQLite i64 range: {n}")
            _write_varint(out, (n << 1) ^ (n >> 63))  # zigzag
        elif tag == T_REAL:
            out += struct.pack(">d", v)
        else:
            data = v.encode("utf-8") if isinstance(v, str) else bytes(v)
            _write_varint(out, len(data))
            out += data
    return bytes(out)


def _py_unpack_columns(blob: bytes) -> tuple[SqliteValue, ...]:
    values: list[SqliteValue] = []
    i = 0
    while i < len(blob):
        tag = blob[i]
        i += 1
        if tag == T_NULL:
            values.append(None)
        elif tag == T_INT:
            z, i = _read_varint(blob, i)
            values.append((z >> 1) ^ -(z & 1))  # un-zigzag
        elif tag == T_REAL:
            if i + 8 > len(blob):
                raise MalformedBlobError(f"truncated real at offset {i}")
            values.append(struct.unpack_from(">d", blob, i)[0])
            i += 8
        elif tag in (T_TEXT, T_BLOB):
            n, i = _read_varint(blob, i)
            if i + n > len(blob):
                raise MalformedBlobError(
                    f"declared length {n} overruns blob at offset {i}"
                )
            data = blob[i : i + n]
            values.append(data.decode("utf-8") if tag == T_TEXT else bytes(data))
            i += n
        else:
            raise MalformedBlobError(f"bad column tag {tag} at offset {i-1}")
    return tuple(values)


# Native fast path (corrosion_tpu/_native, built from native/): byte-exact
# with the Python codec above; MalformedError translates to
# MalformedBlobError so callers see one exception type.
from corrosion_tpu import native as _native_mod  # noqa: E402


def pack_columns(values: Iterable[SqliteValue]) -> bytes:
    """Serialize a tuple of SQL values into one blob (PK encoding).

    Deterministic: equal tuples produce equal blobs, so blobs are usable as
    dictionary keys and DB-stored primary-key identities, like the packed pk
    column in the reference (pubsub.rs:2115+).
    """
    if _native_mod.native is not None:
        return _native_mod.native.pack_columns(values)
    return _py_pack_columns(values)


def unpack_columns(blob: bytes) -> tuple[SqliteValue, ...]:
    if _native_mod.native is not None:
        try:
            return _native_mod.native.unpack_columns(blob)
        except _native_mod.native.MalformedError as e:
            raise MalformedBlobError(str(e)) from None
    return _py_unpack_columns(blob)


def value_le(a: SqliteValue, b: SqliteValue) -> bool:
    """a <= b under the LWW total order (native when built)."""
    if _native_mod.native is not None:
        return _native_mod.native.value_cmp(a, b) <= 0
    return value_cmp_key(a) <= value_cmp_key(b)


def value_cmp_key(v: SqliteValue) -> tuple[int, Any]:
    """Total order over SQL values for LWW tie-breaking.

    "Biggest value wins" on col_version ties (reference doc/crdts.md:15-16):
    SQLite cross-type ordering (NULL < numbers < text < blob), numeric order
    within numbers, lexicographic within text/blob.
    """
    if v is None:
        return (T_NULL, 0)
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return (T_INT, v)  # ints and reals share the numeric class
    if isinstance(v, bool):
        return (T_INT, int(v))
    if isinstance(v, str):
        return (T_TEXT, v)
    return (T_BLOB, v)


@dataclass(frozen=True)
class Change:
    """One CRR cell change (corro-api-types lib.rs:210-238).

    A changeset row: (table, pk, cid) identifies a cell; val/col_version carry
    the LWW payload; db_version/seq place it in the originating actor's
    history; site_id is the originating actor; cl is the row's causal length
    (odd = live, even = deleted).
    """

    table: str
    pk: bytes  # pack_columns of the primary key tuple
    cid: str  # column name; DELETE_CID/PKONLY_CID sentinels for row markers
    val: SqliteValue
    col_version: int
    db_version: int
    seq: int
    site_id: bytes
    cl: int

    # sentinel cid used by the CRR layer for row-level (create/delete) records
    DELETE_CID = "__crsql_del"
    PKONLY_CID = "__crsql_pko"

    def estimated_byte_size(self) -> int:
        """Rough wire size, used for chunking (change.rs byte accounting)."""
        if self.val is None:
            val_len = 0
        elif isinstance(self.val, bytes):
            val_len = len(self.val)
        elif isinstance(self.val, str):
            val_len = len(self.val.encode("utf-8"))
        else:
            val_len = 8
        return 40 + len(self.table) + len(self.pk) + len(self.cid) + val_len

    def to_tuple(self) -> tuple:
        return (
            self.table,
            self.pk,
            self.cid,
            self.val,
            self.col_version,
            self.db_version,
            self.seq,
            self.site_id,
            self.cl,
        )

    @classmethod
    def from_tuple(cls, t: tuple) -> "Change":
        return cls(*t)


@dataclass
class Statement:
    """A SQL statement with optional positional or named params
    (corro-api-types lib.rs:168-195)."""

    sql: str
    params: list[SqliteValue] | None = None
    named_params: dict[str, SqliteValue] | None = None

    @classmethod
    def parse(cls, obj: Any) -> "Statement":
        """Accepts the reference's JSON forms: "sql", ["sql", [params]],
        ["sql", {named}]."""
        if isinstance(obj, str):
            return cls(obj)
        if isinstance(obj, (list, tuple)):
            if len(obj) == 1:
                return cls(obj[0])
            if len(obj) != 2:
                raise ValueError(
                    f"statement array must be [sql], [sql, [params]] or "
                    f"[sql, {{named}}], got {len(obj)} elements"
                )
            sql, second = obj[0], obj[1]
            if isinstance(second, dict):
                return cls(sql, named_params=second)
            if isinstance(second, (list, tuple)):
                return cls(sql, params=list(second))
            raise ValueError(f"statement params must be a list or dict, got {second!r}")
        if isinstance(obj, dict):
            return cls(
                obj["query"],
                params=obj.get("params"),
                named_params=obj.get("named_params"),
            )
        raise ValueError(f"cannot parse statement from {obj!r}")

    def to_json_obj(self) -> Any:
        if self.named_params is not None:
            return [self.sql, self.named_params]
        if self.params is not None:
            return [self.sql, self.params]
        return self.sql


@dataclass
class ExecResult:
    """One statement's outcome inside an /v1/transactions response."""

    rows_affected: int | None = None
    time: float | None = None
    error: str | None = None

    def to_json_obj(self) -> dict:
        if self.error is not None:
            return {"error": self.error}
        return {"rows_affected": self.rows_affected, "time": self.time}


@dataclass
class ExecResponse:
    results: list[ExecResult] = field(default_factory=list)
    time: float = 0.0
    version: int | None = None

    def to_json_obj(self) -> dict:
        out: dict[str, Any] = {
            "results": [r.to_json_obj() for r in self.results],
            "time": self.time,
        }
        if self.version is not None:
            out["version"] = self.version
        return out


# --- Query events (subscription stream frames, corro-api-types lib.rs:25-62) ---


@dataclass(frozen=True)
class QueryEventColumns:
    columns: list[str]

    def to_json_obj(self) -> dict:
        return {"columns": self.columns}


@dataclass(frozen=True)
class QueryEventRow:
    rowid: int
    cells: list[SqliteValue]

    def to_json_obj(self) -> dict:
        return {"row": [self.rowid, self.cells]}


@dataclass(frozen=True)
class QueryEventEndOfQuery:
    time: float
    change_id: int | None = None

    def to_json_obj(self) -> dict:
        return {"eoq": {"time": self.time, "change_id": self.change_id}}


# row-change kinds on the live stream
CHANGE_INSERT, CHANGE_UPDATE, CHANGE_DELETE = "insert", "update", "delete"


@dataclass(frozen=True)
class QueryEventChange:
    kind: str  # insert | update | delete
    rowid: int
    cells: list[SqliteValue]
    change_id: int

    def to_json_obj(self) -> dict:
        return {"change": [self.kind, self.rowid, self.cells, self.change_id]}


@dataclass(frozen=True)
class QueryEventError:
    error: str

    def to_json_obj(self) -> dict:
        return {"error": self.error}


QueryEvent = Union[
    QueryEventColumns,
    QueryEventRow,
    QueryEventEndOfQuery,
    QueryEventChange,
    QueryEventError,
]


def query_event_from_json(obj: dict) -> QueryEvent:
    if "columns" in obj:
        return QueryEventColumns(obj["columns"])
    if "row" in obj:
        rowid, cells = obj["row"]
        return QueryEventRow(rowid, cells)
    if "eoq" in obj:
        eoq = obj["eoq"]
        if isinstance(eoq, dict):
            return QueryEventEndOfQuery(eoq.get("time", 0.0), eoq.get("change_id"))
        return QueryEventEndOfQuery(eoq)
    if "change" in obj:
        kind, rowid, cells, change_id = obj["change"]
        return QueryEventChange(kind, rowid, cells, change_id)
    if "error" in obj:
        return QueryEventError(obj["error"])
    raise ValueError(f"unknown query event {obj!r}")
