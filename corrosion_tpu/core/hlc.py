"""Hybrid logical clock.

Equivalent of the `uhlc` crate used by the reference (corro-types
broadcast.rs:223-319 wraps uhlc's NTP64 Timestamp; the agent builds its HLC
with a 300 ms max clock delta, agent.rs:281-289).

Encoding: a Timestamp is a u64 = (physical_millis << LOGICAL_BITS) | logical
counter (20 bits ≈ 1M logical ticks per millisecond; 44 physical bits cover
several centuries). Comparisons are plain integer comparisons, so timestamps
totally order events across the cluster; ties are broken by actor id at use
sites.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

LOGICAL_BITS = 20
LOGICAL_MASK = (1 << LOGICAL_BITS) - 1
MAX_U64 = (1 << 64) - 1

# Reject remote timestamps further than this ahead of our physical clock
# (uhlc delta; reference uses 300 ms, agent.rs:285).
DEFAULT_MAX_DELTA_MS = 300


def make_ts(physical_ms: int, logical: int = 0) -> int:
    return ((physical_ms << LOGICAL_BITS) | (logical & LOGICAL_MASK)) & MAX_U64


def ts_physical_ms(ts: int) -> int:
    return ts >> LOGICAL_BITS


def ts_logical(ts: int) -> int:
    return ts & LOGICAL_MASK


def ts_to_string(ts: int) -> str:
    return f"{ts_physical_ms(ts)}:{ts_logical(ts)}"


def ts_from_string(s: str) -> int:
    phys, _, logical = s.partition(":")
    return make_ts(int(phys), int(logical or 0))


class ClockDriftError(Exception):
    def __init__(self, ts: int, now_ms: int, max_delta_ms: int):
        super().__init__(
            f"remote timestamp {ts_to_string(ts)} is more than "
            f"{max_delta_ms}ms ahead of local clock ({now_ms}ms)"
        )
        self.ts = ts


@dataclass
class HLC:
    """Thread-safe hybrid logical clock."""

    max_delta_ms: int = DEFAULT_MAX_DELTA_MS
    _last: int = 0

    def __post_init__(self) -> None:
        self._lock = threading.Lock()

    def _now_ms(self) -> int:
        return time.time_ns() // 1_000_000

    def new_timestamp(self) -> int:
        """Monotonic local timestamp: max(wall clock, last+1 logical)."""
        with self._lock:
            wall = make_ts(self._now_ms())
            self._last = wall if wall > self._last else self._last + 1
            return self._last

    def update_with_timestamp(self, ts: int) -> None:
        """Merge a remote timestamp (sync clock exchange, peer.rs:1306-1325).

        Raises ClockDriftError when the remote clock is too far ahead.
        """
        with self._lock:
            now_ms = self._now_ms()
            if ts_physical_ms(ts) > now_ms + self.max_delta_ms:
                raise ClockDriftError(ts, now_ms, self.max_delta_ms)
            if ts > self._last:
                self._last = ts

    @property
    def last(self) -> int:
        with self._lock:
            return self._last
