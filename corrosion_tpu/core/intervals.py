"""Inclusive integer interval sets and maps.

Host-side equivalent of the `rangemap` crate (RangeInclusiveSet /
RangeInclusiveMap) that the reference leans on for version bookkeeping
(corro-types/agent.rs:945-1052) and sync-need computation
(corro-types/sync.rs:123-246). The JAX sim uses fixed-capacity interval
tensors instead (corrosion_tpu.sim.intervals); property tests assert the two
implementations agree.

Ranges are inclusive [start, end] over ints. Adjacent ranges coalesce
([1,3] + [4,5] -> [1,5]); for the map, only when their values are equal.
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right
from typing import Any, Iterable, Iterator


class RangeSet:
    """Sorted, coalesced set of inclusive integer ranges."""

    __slots__ = ("_starts", "_ends")

    def __init__(self, ranges: Iterable[tuple[int, int]] = ()) -> None:
        self._starts: list[int] = []
        self._ends: list[int] = []
        for s, e in ranges:
            self.insert(s, e)

    def __bool__(self) -> bool:
        return bool(self._starts)

    def __len__(self) -> int:
        return len(self._starts)

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, RangeSet):
            return NotImplemented
        return self._starts == other._starts and self._ends == other._ends

    def __iter__(self) -> Iterator[tuple[int, int]]:
        return iter(zip(self._starts, self._ends))

    def __repr__(self) -> str:
        return f"RangeSet({list(self)})"

    def copy(self) -> "RangeSet":
        rs = RangeSet()
        rs._starts = self._starts.copy()
        rs._ends = self._ends.copy()
        return rs

    def insert(self, start: int, end: int) -> None:
        if end < start:
            raise ValueError(f"invalid range [{start}, {end}]")
        # Find all existing ranges overlapping or adjacent to [start-1, end+1]
        # and merge them into one.
        lo = bisect_left(self._ends, start - 1)
        hi = bisect_right(self._starts, end + 1)
        if lo < hi:
            start = min(start, self._starts[lo])
            end = max(end, self._ends[hi - 1])
        self._starts[lo:hi] = [start]
        self._ends[lo:hi] = [end]

    def remove(self, start: int, end: int) -> None:
        if end < start:
            raise ValueError(f"invalid range [{start}, {end}]")
        lo = bisect_left(self._ends, start)
        hi = bisect_right(self._starts, end)
        if lo >= hi:
            return
        new_starts: list[int] = []
        new_ends: list[int] = []
        if self._starts[lo] < start:
            new_starts.append(self._starts[lo])
            new_ends.append(start - 1)
        if self._ends[hi - 1] > end:
            new_starts.append(end + 1)
            new_ends.append(self._ends[hi - 1])
        self._starts[lo:hi] = new_starts
        self._ends[lo:hi] = new_ends

    def contains(self, x: int) -> bool:
        i = bisect_left(self._ends, x)
        return i < len(self._starts) and self._starts[i] <= x

    def contains_range(self, start: int, end: int) -> bool:
        i = bisect_left(self._ends, start)
        return i < len(self._starts) and self._starts[i] <= start and end <= self._ends[i]

    def gaps(self, start: int, end: int) -> Iterator[tuple[int, int]]:
        """Sub-ranges of [start, end] not covered by this set."""
        cursor = start
        i = bisect_left(self._ends, start)
        while cursor <= end and i < len(self._starts):
            s, e = self._starts[i], self._ends[i]
            if s > end:
                break
            if s > cursor:
                yield (cursor, s - 1)
            cursor = max(cursor, e + 1)
            i += 1
        if cursor <= end:
            yield (cursor, end)

    def max_end(self) -> int | None:
        return self._ends[-1] if self._ends else None

    def total(self) -> int:
        return sum(e - s + 1 for s, e in self)


class RangeMap:
    """Sorted map of disjoint inclusive ranges to values.

    Inserting overwrites any overlapped portion of existing ranges (rangemap
    RangeInclusiveMap semantics). Adjacent ranges with equal values coalesce.
    """

    __slots__ = ("_starts", "_ends", "_values")

    def __init__(self, items: Iterable[tuple[int, int, Any]] = ()) -> None:
        self._starts: list[int] = []
        self._ends: list[int] = []
        self._values: list[Any] = []
        for s, e, v in items:
            self.insert(s, e, v)

    def __bool__(self) -> bool:
        return bool(self._starts)

    def __len__(self) -> int:
        return len(self._starts)

    def __iter__(self) -> Iterator[tuple[int, int, Any]]:
        return iter(zip(self._starts, self._ends, self._values))

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, RangeMap):
            return NotImplemented
        return list(self) == list(other)

    def __repr__(self) -> str:
        return f"RangeMap({list(self)})"

    def insert(self, start: int, end: int, value: Any) -> None:
        if end < start:
            raise ValueError(f"invalid range [{start}, {end}]")
        lo = bisect_left(self._ends, start)
        hi = bisect_right(self._starts, end)
        new: list[tuple[int, int, Any]] = []
        if lo < hi:
            s0, e0, v0 = self._starts[lo], self._ends[lo], self._values[lo]
            if s0 < start:
                new.append((s0, start - 1, v0))
            s1, e1, v1 = self._starts[hi - 1], self._ends[hi - 1], self._values[hi - 1]
            if e1 > end:
                new.append((end + 1, e1, v1))
        # splice in the new range between any preserved fragments
        new.append((start, end, value))
        new.sort(key=lambda t: t[0])
        self._starts[lo:hi] = [t[0] for t in new]
        self._ends[lo:hi] = [t[1] for t in new]
        self._values[lo:hi] = [t[2] for t in new]
        self._coalesce_around(lo, lo + len(new))

    def _coalesce_around(self, lo: int, hi: int) -> None:
        i = max(0, lo - 1)
        while i < len(self._starts) - 1 and i <= hi:
            if (
                self._ends[i] + 1 == self._starts[i + 1]
                and self._values[i] == self._values[i + 1]
            ):
                self._ends[i] = self._ends[i + 1]
                del self._starts[i + 1], self._ends[i + 1], self._values[i + 1]
                hi -= 1
            else:
                i += 1

    def remove(self, start: int, end: int) -> None:
        if end < start:
            raise ValueError(f"invalid range [{start}, {end}]")
        lo = bisect_left(self._ends, start)
        hi = bisect_right(self._starts, end)
        if lo >= hi:
            return
        new: list[tuple[int, int, Any]] = []
        if self._starts[lo] < start:
            new.append((self._starts[lo], start - 1, self._values[lo]))
        if self._ends[hi - 1] > end:
            new.append((end + 1, self._ends[hi - 1], self._values[hi - 1]))
        self._starts[lo:hi] = [t[0] for t in new]
        self._ends[lo:hi] = [t[1] for t in new]
        self._values[lo:hi] = [t[2] for t in new]

    def get(self, x: int) -> Any | None:
        i = bisect_left(self._ends, x)
        if i < len(self._starts) and self._starts[i] <= x:
            return self._values[i]
        return None

    def get_range(self, x: int) -> tuple[int, int, Any] | None:
        i = bisect_left(self._ends, x)
        if i < len(self._starts) and self._starts[i] <= x:
            return (self._starts[i], self._ends[i], self._values[i])
        return None

    def overlapping(self, start: int, end: int) -> Iterator[tuple[int, int, Any]]:
        i = bisect_left(self._ends, start)
        while i < len(self._starts) and self._starts[i] <= end:
            yield (self._starts[i], self._ends[i], self._values[i])
            i += 1

    def max_end(self) -> int | None:
        return self._ends[-1] if self._ends else None
