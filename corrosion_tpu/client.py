"""Async HTTP client SDK — the corro-client analogue.

Mirrors crates/corro-client (lib.rs:32-315): execute/query/schema against an
agent's HTTP API, plus `subscribe` returning a line-decoded QueryEvent
stream with reconnect-from-change-id (sub.rs:59-277). Uses raw asyncio
streams (HTTP/1.1 with chunked decoding) so it has zero dependencies.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, AsyncIterator

from corrosion_tpu.core.values import Statement


class ApiError(Exception):
    def __init__(self, status: int, body: str):
        super().__init__(f"HTTP {status}: {body}")
        self.status = status
        self.body = body


class _Response:
    def __init__(self, status: int, headers: dict, reader, writer):
        self.status = status
        self.headers = headers
        self._reader = reader
        self._writer = writer

    async def body(self) -> bytes:
        if "content-length" in self.headers:
            return await self._reader.readexactly(
                int(self.headers["content-length"])
            )
        if self.headers.get("transfer-encoding") == "chunked":
            out = b""
            async for chunk in self.chunks():
                out += chunk
            return out
        return await self._reader.read()

    async def chunks(self) -> AsyncIterator[bytes]:
        while True:
            size_line = await self._reader.readline()
            n = int(size_line.strip() or b"0", 16)
            if n == 0:
                await self._reader.readline()
                return
            data = await self._reader.readexactly(n)
            await self._reader.readexactly(2)  # trailing \r\n
            yield data

    async def lines(self) -> AsyncIterator[bytes]:
        """NDJSON lines across chunk boundaries (LinesBytesCodec)."""
        buf = b""
        async for chunk in self.chunks():
            buf += chunk
            while b"\n" in buf:
                line, _, buf = buf.partition(b"\n")
                if line.strip():
                    yield line
        if buf.strip():
            yield buf

    def close(self) -> None:
        try:
            self._writer.close()
        except Exception:
            pass


class CorrosionApiClient:
    """corro-client's CorrosionApiClient (lib.rs:32-315)."""

    def __init__(self, host: str, port: int) -> None:
        self.host = host
        self.port = port

    async def _request(
        self, method: str, path: str, body: bytes | None = None,
        headers: dict | None = None,
    ) -> _Response:
        reader, writer = await asyncio.open_connection(self.host, self.port)
        extra = "".join(
            f"{k}: {v}\r\n" for k, v in (headers or {}).items()
        )
        head = (
            f"{method} {path} HTTP/1.1\r\n"
            f"host: {self.host}:{self.port}\r\n"
            "content-type: application/json\r\n"
            f"content-length: {len(body or b'')}\r\n"
            f"{extra}\r\n"
        )
        writer.write(head.encode() + (body or b""))
        await writer.drain()
        status_line = await reader.readline()
        status = int(status_line.split()[1])
        headers = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            k, _, v = h.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        return _Response(status, headers, reader, writer)

    async def execute(
        self, statements: list[Statement | str | list],
        traceparent: str | None = None,
    ) -> dict:
        """POST /v1/transactions. ``traceparent`` (a W3C header value)
        seeds the server's causal write trace with the CALLER's trace id
        — how a load generator's delivery records later join the agent's
        span export (docs/OBSERVABILITY.md "Causal tracing")."""
        body = json.dumps(
            [
                s.to_json_obj() if isinstance(s, Statement) else s
                for s in statements
            ]
        ).encode()
        resp = await self._request(
            "POST", "/v1/transactions", body,
            headers=(
                {"traceparent": traceparent} if traceparent else None
            ),
        )
        data = await resp.body()
        resp.close()
        if resp.status != 200:
            raise ApiError(resp.status, data.decode())
        return json.loads(data)

    async def query(
        self, statement: Statement | str
    ) -> tuple[list[str], list[list[Any]]]:
        st = (
            statement
            if isinstance(statement, Statement)
            else Statement(statement)
        )
        resp = await self._request(
            "POST", "/v1/queries", json.dumps(st.to_json_obj()).encode()
        )
        if resp.status != 200:
            data = await resp.body()
            resp.close()
            raise ApiError(resp.status, data.decode())
        cols: list[str] = []
        rows: list[list[Any]] = []
        async for line in resp.lines():
            ev = json.loads(line)
            if "columns" in ev:
                cols = ev["columns"]
            elif "row" in ev:
                rows.append(ev["row"][1])
            elif "eoq" in ev:
                break
            elif "error" in ev:
                resp.close()
                raise ApiError(500, ev["error"])
        resp.close()
        return cols, rows

    async def schema(self, ddl: list[str]) -> dict:
        resp = await self._request(
            "POST", "/v1/migrations", json.dumps(ddl).encode()
        )
        data = await resp.body()
        resp.close()
        if resp.status != 200:
            raise ApiError(resp.status, data.decode())
        return json.loads(data)

    async def subscribe(
        self, sql: str, skip_rows: bool = False
    ) -> "SubscriptionStream":
        q = "?skip_rows=true" if skip_rows else ""
        resp = await self._request(
            "POST", f"/v1/subscriptions{q}",
            json.dumps(sql).encode(),
        )
        if resp.status != 200:
            data = await resp.body()
            resp.close()
            raise ApiError(resp.status, data.decode())
        return SubscriptionStream(self, resp)

    async def resubscribe(
        self, sub_id: str, from_change: int | None = None
    ) -> "SubscriptionStream":
        q = f"?from={from_change}" if from_change is not None else ""
        resp = await self._request("GET", f"/v1/subscriptions/{sub_id}{q}")
        if resp.status != 200:
            data = await resp.body()
            resp.close()
            raise ApiError(resp.status, data.decode())
        return SubscriptionStream(self, resp, sub_id=sub_id)


class SubscriptionStream:
    """Decoded QueryEvent stream with observed-change-id tracking, so a
    dropped connection can resume via `?from=` (corro-client sub.rs:59-277)."""

    def __init__(self, client, resp: _Response, sub_id: str | None = None):
        self._client = client
        self._resp = resp
        self.sub_id = sub_id
        self.last_change_id: int | None = None
        self._lines = resp.lines()

    def __aiter__(self):
        return self

    async def __anext__(self) -> dict:
        async for line in self._lines:
            ev = json.loads(line)
            if "sub_id" in ev:
                self.sub_id = ev["sub_id"]
                continue
            if "change" in ev:
                self.last_change_id = ev["change"][3]
            return ev
        raise StopAsyncIteration

    async def reconnect(
        self, retries: int = 0, delay_s: float = 0.2
    ) -> None:
        """Resume from the last observed change id.

        ``retries`` re-attempts the resubscribe on connection failure
        (an agent mid-restart refuses connections for a moment; the
        durable sub-db makes the resume valid once it is back). The
        stream's resume state (sub_id, last_change_id) is untouched on
        failure, so a later call retries from the same point.
        """
        if self.sub_id is None:
            raise ApiError(400, "no sub_id observed yet")
        self.close()
        attempt = 0
        while True:
            try:
                fresh = await self._client.resubscribe(
                    self.sub_id, from_change=self.last_change_id
                )
                break
            except (ConnectionError, OSError):
                if attempt >= retries:
                    raise
                attempt += 1
                await asyncio.sleep(delay_s)
        self._resp = fresh._resp
        self._lines = fresh._lines

    def close(self) -> None:
        self._resp.close()
