"""Elastic-plane artifacts and the budget gate.

Scenario reports (elastic/scenarios.py) carry ``corro-elastic/1``; the
smoke lane (scripts/elastic_smoke.py) wraps a batch of them and gates
against the ``elastic`` entry of bench_budget.json in the standing
soak/hostchaos style: wall ceilings scale with the budget's tolerance,
the survival invariants NEVER scale — bit-identity, byte-exact
reconcile, zero oracle violations, and the machinery-fired rule are
pass/fail at any tolerance.
"""

from __future__ import annotations

import jax
import numpy as np

# Per-round wire-volume keys legitimately differ across meshes (the
# queue exchange crosses different boundaries on a different device
# grid); every cross-mesh curve compare skips them, same-mesh compares
# keep them.
from corrosion_tpu.sim.telemetry import XSHARD_CURVE_KEYS  # noqa: F401

ELASTIC_SCHEMA = "corro-elastic/1"


def _path_str(path) -> str:
    return jax.tree_util.keystr(path) or "<root>"


def diff_trees(a, b, label: str = "") -> list:
    """Leaf-by-leaf bit-exact comparison of two state pytrees (host or
    device; NaN != NaN, matching the convergence contract — final CRDT
    state is all-integer). Returns human-readable mismatch strings,
    empty = identical."""
    fa = jax.tree_util.tree_flatten_with_path(a)[0]
    fb = jax.tree_util.tree_flatten_with_path(b)[0]
    if len(fa) != len(fb):
        return [f"{label}: structure differs ({len(fa)} vs {len(fb)} leaves)"]
    out = []
    for (pa, la), (_pb, lb) in zip(fa, fb):
        xa, xb = np.asarray(la), np.asarray(lb)
        if xa.shape != xb.shape or xa.dtype != xb.dtype:
            out.append(
                f"{label}{_path_str(pa)}: {xa.dtype}{xa.shape} vs "
                f"{xb.dtype}{xb.shape}"
            )
        elif not np.array_equal(xa, xb):
            bad = int(np.sum(xa != xb))
            out.append(
                f"{label}{_path_str(pa)}: {bad}/{xa.size} elements differ"
            )
    return out


def slice_curves(curves: dict, start: int, stop: int | None = None) -> dict:
    """Round-window view of a per-round curve dict."""
    return {k: np.asarray(v)[start:stop] for k, v in curves.items()}


def diff_curves(a: dict, b: dict, skip: tuple = ()) -> list:
    """Bit-exact comparison of two per-round curve dicts; ``skip``
    names keys excused from the compare (pass ``XSHARD_CURVE_KEYS``
    when the two sides ran on different meshes)."""
    out = []
    keys = sorted(set(a) | set(b))
    for k in keys:
        if k in skip:
            continue
        if k not in a or k not in b:
            out.append(f"curve {k}: present on one side only")
            continue
        xa, xb = np.asarray(a[k]), np.asarray(b[k])
        if xa.shape != xb.shape:
            out.append(f"curve {k}: shape {xa.shape} vs {xb.shape}")
        elif not np.array_equal(xa, xb):
            first = int(np.flatnonzero(
                np.any((xa != xb).reshape(xa.shape[0], -1), axis=1)
            )[0])
            out.append(f"curve {k}: diverges at round {first}")
    return out


def wall_total(scenario: dict) -> float:
    return float(sum((scenario.get("wall_s") or {}).values()))


def check_elastic_budget(report: dict, budget: dict) -> dict:
    """Gate a smoke-lane report against the ``elastic`` budget entry.

    Scaled by ``tolerance``: per-scenario wall ceilings (noisy CI
    hosts). NEVER scaled: ``require_bit_identical``,
    ``require_reconcile``, ``require_machinery_fired``,
    ``oracle_violations_max`` — a slow reshard is a warning, a
    divergent one is a broken survival plane. A scenario the budget
    names but the report lacks is a breach (a lane that silently stops
    running a scenario must fail loudly — the machinery-fired
    principle applied to the harness itself)."""
    tol = float(budget.get("tolerance", 1.0))
    breaches: list = []
    checks: list = []
    by_name = {
        s.get("scenario"): s for s in report.get("scenarios", [])
    }
    for name, sb in (budget.get("scenarios") or {}).items():
        s = by_name.get(name)
        if s is None:
            breaches.append(f"{name}: scenario missing from report")
            continue
        if budget.get("require_bit_identical", 1) and not s.get(
            "bit_identical", False
        ):
            breaches.append(
                f"{name}: NOT bit-identical to the uninterrupted run "
                f"({len(s.get('mismatches', []))} mismatches)"
            )
        if budget.get("require_reconcile", 1) and not (
            (s.get("reconcile") or {}).get("ok", False)
        ):
            breaches.append(
                f"{name}: predicted_per_device_bytes did not reconcile"
            )
        viol = len(s.get("violations") or [])
        if viol > int(budget.get("oracle_violations_max", 0)):
            breaches.append(f"{name}: {viol} oracle violation(s)")
        mach = s.get("machinery")
        if mach is not None and budget.get("require_machinery_fired", 1):
            if not mach.get("fired", False):
                breaches.append(
                    f"{name}: passed with recovery machinery idle — "
                    f"harness failure ({mach})"
                )
        ceiling = sb.get("wall_ceiling_s")
        if ceiling is not None:
            wall = wall_total(s)
            checks.append({
                "scenario": name, "wall_s": wall,
                "wall_ceiling_s": ceiling * tol,
            })
            if wall > ceiling * tol:
                breaches.append(
                    f"{name}: wall {wall:.1f}s > ceiling "
                    f"{ceiling * tol:.1f}s (tolerance {tol}x)"
                )
        if not s.get("ok", False):
            breaches.append(f"{name}: scenario reported not ok")
    return {
        "ok": not breaches,
        "breaches": breaches,
        "checks": checks,
        "tolerance": tol,
    }
