"""Device-shard preemption: kill a shard's state mid-run, recover from
the last checkpoint, prove nothing was lost.

The fault model mirrors ``Agent.abort`` crash semantics (agent.rs): the
preempted device gets NO graceful drain — its block of every sharded
leaf is destroyed at the event round, full stop. Recovery is the only
path back: re-materialize the lost shard from the most recent
checkpoint and replay the gap rounds. The harness makes the kill real
(the poisoned state is materialized and diffed against the live one —
a "preemption" that changes no bytes is a harness bug) and the recovery
honest (the replayed gap's round curves must be bit-identical to the
originals; deterministic replay is the whole basis of the scheme).

Preempt events live on the fault plane (sim/faults.py ``preempt`` kind)
but execute HERE, host-side: ``FaultPlan.compile`` skips them (nothing
about the kernel changes when a host dies), ``FaultPlan.kernel_plan()``
strips them from what the engines see, and ``preempt_events()`` is this
driver's worklist. Scenario-level oracles — CRDT serial-merge
agreement, bookkeeping contiguity, incarnation monotonicity, and final
bit-identity against the uninterrupted same-seed run — live in
elastic/scenarios.py; the machinery-fired rule (a passing scenario with
idle recovery counters is a harness failure) keys off RecoveryCounters.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from corrosion_tpu.elastic.reshard import (
    _ckpt_path,
    mesh_dims,
    place_reconciled,
    schedule_slice,
)
from corrosion_tpu.parallel import mesh as mesh_mod
from corrosion_tpu.parallel import shard_driver
from corrosion_tpu.sim import checkpoint as checkpoint_mod


@dataclass
class RecoveryCounters:
    """Did the recovery machinery actually run? A preemption scenario
    that passes with these at zero proves nothing — the machinery-fired
    rule (obs/endurance.py precedent) turns that into a failure."""

    preempts_fired: int = 0
    checkpoint_loads: int = 0
    shards_rematerialized: int = 0
    gap_rounds_replayed: int = 0

    def fired(self) -> bool:
        return (
            self.preempts_fired > 0
            and self.checkpoint_loads > 0
            and self.shards_rematerialized > 0
        )

    def to_dict(self) -> dict:
        return {
            "preempts_fired": self.preempts_fired,
            "checkpoint_loads": self.checkpoint_loads,
            "shards_rematerialized": self.shards_rematerialized,
            "gap_rounds_replayed": self.gap_rounds_replayed,
            "fired": self.fired(),
        }


def _garbage(dtype):
    dt = np.dtype(dtype)
    if dt.kind == "b":
        return True
    if dt.kind in "iu":
        return np.iinfo(dt).max
    return np.nan


def poison_lost_shard(host_tree, specs, mesh, device_index: int):
    """Destroy device ``device_index``'s block of every sharded leaf in
    a host copy of the state — dtype-extreme garbage (True / int max /
    NaN), no drain. Replicated leaves survive (the other replicas still
    hold them — exactly why writer heads and slot metadata replicate).
    Returns ``(poisoned_tree, n_leaves_poisoned)``.

    The block↔device mapping relies on the repo-wide invariant that
    every sharded leaf splits ONE dim by the full device count (the
    node-major row blocks of mesh.py's spec builders), so block ``i``
    in C-order is device ``i`` in ``mesh.devices``. Anything fancier is
    refused rather than silently mis-poisoned."""
    d = int(mesh.devices.size)
    if not 0 <= device_index < d:
        raise ValueError(f"device {device_index} outside mesh of {d}")
    leaves, treedef = jax.tree.flatten(host_tree)
    spec_leaves = jax.tree.flatten(
        specs, is_leaf=lambda x: isinstance(x, P)
    )[0]
    out, poisoned = [], 0
    for arr, spec in zip(leaves, spec_leaves):
        arr = np.array(arr)
        sharded = []
        for dim, entry in enumerate(spec):
            if entry is None:
                continue
            f = mesh_mod.spec_shard_factor(P(entry), mesh)
            if f > 1:
                sharded.append((dim, f))
        if not sharded:
            out.append(arr)
            continue
        if len(sharded) != 1 or sharded[0][1] != d:
            raise NotImplementedError(
                f"poison_lost_shard only handles one dim split {d} ways; "
                f"got {spec} on {mesh_dims(mesh)}"
            )
        dim, f = sharded[0]
        block = arr.shape[dim] // f
        sl = [slice(None)] * arr.ndim
        sl[dim] = slice(device_index * block, (device_index + 1) * block)
        arr[tuple(sl)] = _garbage(arr.dtype)
        poisoned += 1
        out.append(arr)
    return jax.tree.unflatten(treedef, out), poisoned


@dataclass
class PreemptRun:
    """One preempted-and-recovered dense run: the final state, stitched
    curves (replay segments verified bit-identical to the originals
    before stitching), and the recovery evidence."""

    rounds: int
    events: list  # [(round, device)]
    checkpoint_every: int
    final: object
    curves: dict
    counters: RecoveryCounters
    facts: dict = field(default_factory=dict)
    wall_s: dict = field(default_factory=dict)


def run_dense_preempted(
    cfg,
    topo,
    sched,
    mesh,
    events,
    checkpoint_every: int,
    seed: int = 0,
    checkpoint_dir: str | None = None,
    fingerprint: str = "",
    telemetry=None,
) -> PreemptRun:
    """Dense run under device-shard preemption: advance in
    ``checkpoint_every``-aligned segments, snapshot at each boundary,
    and at each ``(round, device)`` event kill that device's shard,
    reload the latest checkpoint, replay the gap (pinning the replayed
    curves bit-identical to the first pass), and continue.

    ``events`` is a ``FaultPlan.preempt_events()`` worklist (or any
    sorted ``[(round, device)]``); kernel-plane faults in the same plan
    go to the engine separately via ``FaultPlan.kernel_plan()``."""
    from corrosion_tpu.sim import engine

    ce = int(checkpoint_every)
    if ce <= 0:
        raise ValueError("checkpoint_every must be positive")
    events = sorted((int(r), int(d)) for r, d in events)
    rounds = sched.rounds
    for p_round, _dev in events:
        if not 0 <= p_round < rounds:
            raise ValueError(f"preempt round {p_round} outside run")

    counters = RecoveryCounters()
    wall = {"advance": 0.0, "checkpoint": 0.0, "recover": 0.0}
    segs: dict = {}  # start round -> curves (np) for bit-identity replay
    replay_mismatches: list = []
    checkpoints_taken: list = []
    reconciles: list = []
    n_samples = len(sched.sample_writer)

    state = mesh_mod.shard_cluster_state(
        engine.init_cluster(cfg, n_samples), mesh
    )
    ckpt_round, ckpt_host = 0, jax.device_get(state)

    def specs_for(host):
        return mesh_mod.cluster_state_specs(host, mesh)

    def take_checkpoint(state, r):
        nonlocal ckpt_round, ckpt_host
        t = time.perf_counter()
        host = jax.device_get(state)
        path = _ckpt_path(checkpoint_dir, f"preempt_r{r}.npz")
        if path is not None:
            checkpoint_mod.save_state(
                path, host, fingerprint=fingerprint,
                mesh_shape=mesh_dims(mesh),
            )
            host = checkpoint_mod.load_state(
                path, cfg, n_samples, expect_fingerprint=fingerprint
            )
        ckpt_round, ckpt_host = r, host
        checkpoints_taken.append(r)
        wall["checkpoint"] += time.perf_counter() - t

    def advance(state, r_from, r_to, replay: bool):
        """Segment-wise advance hitting every grid boundary, so the
        replay path recompiles nothing and checkpoints land exactly
        where the first pass took them."""
        kind = "recover" if replay else "advance"
        r = r_from
        while r < r_to:
            t = time.perf_counter()
            nxt = min(r_to, (r // ce + 1) * ce)
            state, curves = shard_driver.simulate_sharded(
                cfg, topo, schedule_slice(sched, r, nxt), mesh,
                seed=seed, state=state, telemetry=telemetry,
            )
            curves = {k: np.asarray(v) for k, v in curves.items()}
            if replay and r in segs:
                bad = [
                    k for k in segs[r]
                    if not np.array_equal(segs[r][k], curves[k])
                ]
                if bad:
                    replay_mismatches.append({"round": r, "keys": bad})
            segs[r] = curves
            wall[kind] += time.perf_counter() - t
            r = nxt
            if not replay and r % ce == 0 and r < r_to:
                take_checkpoint(state, r)
        return state

    poison_changed = True
    r = 0
    for p_round, device in events:
        state = advance(state, r, p_round, replay=False)
        if p_round % ce == 0 and p_round > r:
            # advance() skips the boundary that coincides with its end;
            # the event interrupts the run exactly there, so the
            # snapshot the recovery needs is this one.
            take_checkpoint(state, p_round)

        # The kill: materialize what the cluster would hold with this
        # device's shard destroyed, and prove the destruction is real.
        counters.preempts_fired += 1
        live_host = jax.device_get(state)
        poisoned, n_leaves = poison_lost_shard(
            live_host, specs_for(live_host), mesh, device
        )
        changed = any(
            not np.array_equal(a, b, equal_nan=False)
            for a, b in zip(
                jax.tree.leaves(live_host), jax.tree.leaves(poisoned)
            )
        )
        poison_changed = poison_changed and changed and n_leaves > 0
        del state, poisoned  # the live state died with the device

        # Recovery: latest checkpoint + deterministic gap replay. The
        # poisoned state is never read — there is nothing to drain.
        t = time.perf_counter()
        counters.checkpoint_loads += 1
        state, rec = place_reconciled(
            ckpt_host, specs_for(ckpt_host), mesh
        )
        reconciles.append({**rec, "round": ckpt_round})
        counters.shards_rematerialized += 1
        wall["recover"] += time.perf_counter() - t
        counters.gap_rounds_replayed += p_round - ckpt_round
        state = advance(state, ckpt_round, p_round, replay=True)
        r = p_round

    state = advance(state, r, rounds, replay=False)
    starts = sorted(segs)
    curves = {
        k: np.concatenate([segs[s][k] for s in starts])
        for k in segs[starts[0]]
    } if starts else {}
    return PreemptRun(
        rounds=rounds, events=events, checkpoint_every=ce, final=state,
        curves=curves, counters=counters,
        facts={
            "poison_changed": bool(poison_changed),
            "replay_identical": not replay_mismatches,
            "replay_mismatches": replay_mismatches,
            "checkpoints": checkpoints_taken,
            "reconciles": reconciles,
        },
        wall_s=wall,
    )
