"""The elastic scenario catalog: named survival drills with their
oracles baked in.

Two families, both emitting ``corro-elastic/1`` report dicts:

- **reshard_<engine>_<D>to<D'>** — checkpoint at a chunk boundary on a
  D-device mesh, re-place on D′, resume; oracle = bit-identity of the
  final state against the uninterrupted same-seed run on the target
  mesh, tail curves compared bit-exact (prefix curves too, minus the
  mesh-dependent xshard byte keys), and the byte-exact
  ``predicted_per_device_bytes`` reconcile from elastic/reshard.py.
  The required dense matrix covers {4→8, 8→4, 8→2, 1→8}
  (``RESHARD_MATRIX``); the other engines each run one 4→8 drill.

- **preempt_dense_churn** — the invariant suite's standard dense churn
  scenario with ``preempt`` events layered on the fault plane: a device
  shard hard-dies mid-run (twice), recovery replays from checkpoints,
  and the run must STILL pass every dense invariant (CRDT serial-merge
  agreement, durability contiguity, incarnation monotonicity) AND end
  bit-identical to the never-preempted run — plus the machinery-fired
  rule: recovery counters at zero fail the scenario even if everything
  else passes.

``soak_preempt`` is the endurance tie-in: the same preempted run feeds
a deterministic metric series whose counters reset at each recovery
(the relaunched process starts from zero) through a re-``attach()``-ed
recorder; the endurance detectors must classify every reset as a
*restart* — not a leak, wedge, or counter anomaly.
"""

from __future__ import annotations

import numpy as np

from corrosion_tpu.elastic import preempt as preempt_mod
from corrosion_tpu.elastic import report as report_mod
from corrosion_tpu.elastic import reshard as reshard_mod
from corrosion_tpu.elastic.report import ELASTIC_SCHEMA

# The required dense coverage: grow, shrink, deep-shrink (8→2 leaves
# the 2-D WAN mesh for the 1-D), and cold single-device restore onto a
# full mesh.
RESHARD_MATRIX = ((4, 8), (8, 4), (8, 2), (1, 8))

RESHARD_ENGINES = ("dense", "sparse", "chunk", "mixed")

# One preempted device per event; two events so the second recovery
# proves checkpoints taken AFTER a recovery work too.
PREEMPT_EVENTS = ((18, 6), (31, 1))
PREEMPT_ROUNDS = 48
PREEMPT_CHECKPOINT_EVERY = 12


def scenario_names() -> list:
    names = [
        f"reshard_dense_{a}to{b}" for a, b in RESHARD_MATRIX
    ] + [
        f"reshard_{e}_4to8" for e in RESHARD_ENGINES if e != "dense"
    ]
    names += ["preempt_dense_churn", "soak_preempt"]
    return names


def _fingerprint(*parts) -> str:
    from corrosion_tpu.sim import benchlib

    return benchlib.config_fingerprint("elastic", *parts)


def _dense_setup():
    """The test_parallel_mesh WAN workload at n=64 (divisible by every
    mesh size in the matrix): partitioned 4-region gossip, 16 writers,
    24 rounds."""
    from corrosion_tpu import models

    cfg, topo, sched = models.wan_100k(
        n=64, n_regions=4, n_writers=16, rounds=24, samples=16
    )
    sched.writes[:8, :] = 1
    sched = sched.make_samples(16)
    return cfg, topo, sched


def run_reshard_scenario(
    engine: str,
    d_from: int,
    d_to: int,
    seed: int = 0,
    checkpoint_dir: str | None = None,
) -> dict:
    """One reshard drill; requires ``max(d_from, d_to)`` devices."""
    import jax

    from corrosion_tpu.parallel import shard_driver

    name = f"reshard_{engine}_{d_from}to{d_to}"
    mesh_from = reshard_mod.virtual_mesh(d_from)
    mesh_to = reshard_mod.virtual_mesh(d_to)
    cross_mesh_skip = report_mod.XSHARD_CURVE_KEYS

    if engine == "dense":
        cfg, topo, sched = _dense_setup()
        split = sched.rounds // 2
        fp = _fingerprint(engine, cfg, d_from, d_to, seed)
        run = reshard_mod.run_dense_resharded(
            cfg, topo, sched, mesh_from, mesh_to, split, seed=seed,
            checkpoint_dir=checkpoint_dir, fingerprint=fp,
        )
        ref_final, ref_curves = shard_driver.simulate_sharded(
            cfg, topo, sched, mesh_to, seed=seed
        )
    elif engine == "sparse":
        from corrosion_tpu.models.baselines import anywrite_sparse

        cfg, topo, sched = anywrite_sparse(
            n=64, w_hot=8, rounds=32, n_regions=4, epoch_rounds=8,
            cohort=4, burst_writes=2, samples=32, k_dev=16,
            partition=True, seed=seed,
        )
        fp = _fingerprint(engine, cfg, d_from, d_to, seed)
        run = reshard_mod.run_sparse_resharded(
            cfg, topo, sched, mesh_from, mesh_to, split_epoch=2,
            seed=seed, checkpoint_dir=checkpoint_dir, fingerprint=fp,
        )
        split = run.split
        *ref_state, ref_curves, _info = shard_driver.simulate_sparse_sharded(
            cfg, topo, sched, mesh_to, seed=seed
        )
        ref_final = tuple(ref_state)
    elif engine == "chunk":
        from corrosion_tpu.ops.chunks import ChunkConfig

        ccfg = ChunkConfig(
            n_nodes=64, n_streams=3, cap=16, chunk_len=128, fanout=3,
            k_in=6, sync_interval=4, gap_requests=4,
            sync_seq_budget=2048,
        )
        origin = np.asarray([0, 21, 42], np.int32)
        last_seq = np.full(3, 1023, np.int32)
        rounds, split = 24, 12
        fp = _fingerprint(engine, ccfg, d_from, d_to, seed)
        run = reshard_mod.run_chunks_resharded(
            ccfg, origin, last_seq, rounds, mesh_from, mesh_to, split,
            seed=seed, checkpoint_dir=checkpoint_dir, fingerprint=fp,
        )
        ref_state, ref_m = shard_driver.simulate_chunks_sharded(
            ccfg, origin, last_seq, rounds, mesh_to, seed=seed
        )
        ref_final, ref_curves = (ref_state, ref_m["vis"]), ref_m["curves"]
    elif engine == "mixed":
        from corrosion_tpu.sim import invariants as inv
        from corrosion_tpu.sim.faults import FaultPlan

        cfg, ccfg, topo, sched, spec = inv._mixed_scenario(
            FaultPlan(rounds=24, name="elastic-mixed"), seed
        )
        split = 12
        fp = _fingerprint(engine, cfg, ccfg, d_from, d_to, seed)
        run = reshard_mod.run_mixed_resharded(
            cfg, ccfg, topo, sched, spec, mesh_from, mesh_to, split,
            seed=seed, checkpoint_dir=checkpoint_dir, fingerprint=fp,
        )
        ref_final, ref_curves = shard_driver.simulate_mixed_sharded(
            cfg, ccfg, topo, sched, spec, mesh_to, seed=seed
        )
    else:
        raise ValueError(f"unknown engine {engine!r}")

    mismatches = report_mod.diff_trees(
        jax.device_get(run.final), jax.device_get(ref_final), "final."
    )
    # Prefix ran on the source mesh: compare minus the mesh-dependent
    # wire-volume keys. Tail ran on the SAME mesh as the reference:
    # every key must match bit-exact, xshard included.
    mismatches += [
        f"prefix {m}" for m in report_mod.diff_curves(
            run.prefix_curves,
            report_mod.slice_curves(ref_curves, 0, split),
            skip=cross_mesh_skip,
        )
    ]
    mismatches += [
        f"tail {m}" for m in report_mod.diff_curves(
            run.tail_curves, report_mod.slice_curves(ref_curves, split)
        )
    ]
    ok = not mismatches and run.reconcile.get("ok", False)
    return {
        "schema": ELASTIC_SCHEMA,
        "scenario": name,
        "kind": "reshard",
        "engine": engine,
        "d_from": d_from,
        "d_to": d_to,
        "split": run.split,
        "bit_identical": not mismatches,
        "mismatches": mismatches[:20],
        "reconcile": run.reconcile,
        "checkpoint": run.checkpoint,
        "violations": [],
        "wall_s": run.wall_s,
        "seed": seed,
        "ok": bool(ok),
    }


def _preempt_plan():
    from corrosion_tpu.sim.faults import Fault, FaultPlan

    return FaultPlan(
        rounds=PREEMPT_ROUNDS,
        name="preempt_dense_churn",
        faults=(
            Fault("churn", 10, 11, nodes=(5, 29), revive_at=22),
            Fault("loss", 12, 24, prob=0.3, regions=(1,)),
            Fault("preempt", PREEMPT_EVENTS[0][0],
                  PREEMPT_EVENTS[0][0] + 1, device=PREEMPT_EVENTS[0][1]),
            Fault("preempt", PREEMPT_EVENTS[1][0],
                  PREEMPT_EVENTS[1][0] + 1, device=PREEMPT_EVENTS[1][1]),
        ),
    )


def run_preempt_scenario(
    seed: int = 0,
    devices: int = 8,
    checkpoint_dir: str | None = None,
    _return_run: bool = False,
):
    """Device-shard preemption over the invariant suite's dense churn
    workload. Oracles: full dense invariant suite on the final state,
    bit-identity against the never-preempted run, recovery machinery
    fired, gap replays bit-identical."""
    import jax

    from corrosion_tpu.ops import gossip
    from corrosion_tpu.parallel import shard_driver
    from corrosion_tpu.sim import faults as faults_mod
    from corrosion_tpu.sim import invariants as inv

    plan = _preempt_plan()
    cfg, topo, sched = inv._dense_scenario(plan, seed)
    compiled = inv._densify(
        plan.kernel_plan().compile(inv.STD_NODES, inv.STD_REGIONS),
        inv.STD_NODES, inv.STD_REGIONS,
    )
    sched = faults_mod.apply_plan(
        sched, compiled, inv.STD_NODES, inv.STD_REGIONS
    )
    mesh = reshard_mod.virtual_mesh(devices)
    fp = _fingerprint("preempt", cfg, devices, seed)
    run = preempt_mod.run_dense_preempted(
        cfg, topo, sched, mesh, plan.preempt_events(),
        PREEMPT_CHECKPOINT_EVERY, seed=seed,
        checkpoint_dir=checkpoint_dir, fingerprint=fp,
    )

    # Oracle 1: bit-identity vs the uninterrupted run on the same mesh.
    ref_final, ref_curves = shard_driver.simulate_sharded(
        cfg, topo, sched, mesh, seed=seed
    )
    final = jax.device_get(run.final)
    mismatches = report_mod.diff_trees(
        final, jax.device_get(ref_final), "final."
    )
    mismatches += report_mod.diff_curves(run.curves, ref_curves)

    # Oracle 2: the dense invariant suite (survival must not cost
    # correctness — serial-merge agreement, durability, monotone
    # incarnations all still hold after two recoveries).
    rep = inv._base_report("dense", plan, compiled, run.curves, cfg.round_ms)
    alive = np.asarray(final.swim.alive)
    inv._check_liveness(rep, plan, alive)
    inv._check_durability(
        rep, alive, np.asarray(final.data.head),
        np.asarray(final.data.contig),
    )
    if cfg.gossip.n_cells > 0:
        ref = gossip.serial_merge_reference(final.data.head, cfg.gossip)
        pc = gossip.node_cells(final.data, cfg.gossip)
        inv._check_cell_agreement(
            rep, pc.cl, pc.col_version, pc.value_rank, ref, alive,
            "serial merge",
        )
    inv._check_no_resurrection(rep, plan, final.swim)
    rep.ok = not rep.violations

    # Oracle 3: machinery-fired — and the kill must have been real.
    machinery = {
        **run.counters.to_dict(),
        "poison_changed": run.facts["poison_changed"],
        "replay_identical": run.facts["replay_identical"],
    }
    recs = run.facts["reconciles"]
    reconcile = {
        "ok": bool(recs) and all(r.get("ok") for r in recs),
        "count": len(recs),
        "predicted_per_device_bytes": (
            recs[0]["predicted_per_device_bytes"] if recs else None
        ),
    }
    ok = (
        rep.ok
        and not mismatches
        and run.counters.fired()
        and run.facts["poison_changed"]
        and run.facts["replay_identical"]
        and reconcile["ok"]
    )
    result = {
        "schema": ELASTIC_SCHEMA,
        "scenario": "preempt_dense_churn",
        "kind": "preempt",
        "engine": "dense",
        "devices": devices,
        "rounds": run.rounds,
        "round_ms": float(cfg.round_ms),
        "checkpoint_every": run.checkpoint_every,
        "events": [list(e) for e in run.events],
        "plan": plan.describe(),
        "bit_identical": not mismatches,
        "mismatches": mismatches[:20],
        "violations": list(rep.violations),
        "recovery": rep.recovery,
        "machinery": machinery,
        "reconcile": reconcile,
        "checkpoints": run.facts["checkpoints"],
        "wall_s": run.wall_s,
        "seed": seed,
        "ok": bool(ok),
    }
    return (result, run) if _return_run else result


def run_soak_preempt_scenario(
    series_path: str,
    seed: int = 0,
    devices: int = 8,
) -> dict:
    """Preemption during a soak: the preempted dense run's curves feed
    a deterministic metric series (one sample per round, counters
    cumulative and RESET at each recovery — a relaunched process starts
    from zero), through a recorder that is re-``attach()``-ed at every
    event (the idempotent-install contract across an in-process
    reshard). The endurance detectors must stay armed AND classify
    every reset as a restart — zero fake leaks/wedges/stalls."""
    from corrosion_tpu.obs import endurance
    from corrosion_tpu.obs import series as series_mod
    from corrosion_tpu.utils.metrics import MetricsRegistry

    scen, run = run_preempt_scenario(
        seed=seed, devices=devices, _return_run=True
    )

    msgs = np.asarray(run.curves["msgs"], np.float64)
    applied = np.asarray(run.curves["applied_broadcast"], np.float64)
    need = np.asarray(run.curves["need"], np.float64)
    event_rounds = {r for r, _ in run.events}

    rec = series_mod.MetricSeriesRecorder.attach(
        series_path, clock=None, source="elastic-soak", mode="w"
    )
    adoption_ok = True
    attaches = 1
    try:
        reg = MetricsRegistry()
        for r in range(run.rounds):
            if r in event_rounds:
                # The preempted process is replaced: counters restart
                # from zero; the series recorder must be ADOPTED, not
                # reopened (no duplicate header, no torn record).
                reg = MetricsRegistry()
                rec2 = series_mod.MetricSeriesRecorder.attach(series_path)
                attaches += 1
                adoption_ok = adoption_ok and (rec2 is rec)
            reg.counter("corro_changes_committed").inc(float(msgs[r]))
            reg.counter("corro_changes_applied").inc(float(applied[r]))
            reg.gauge("corro_sync_needs").set(float(need[r]))
            rec.sample(reg, t=float(r))
    finally:
        # attach() refcounts: one close per successful attach.
        for _ in range(attaches):
            rec.close()

    data = series_mod.replay_series(series_path)
    samples = data["samples"]
    erep = endurance.build_report(
        samples, t_scale_s=scen["round_ms"] / 1000.0,
        label="elastic-soak-preempt",
    )

    violations: list = list(scen["violations"])
    if len(data["headers"]) != 1:
        violations.append(
            f"{len(data['headers'])} series headers — re-attach across "
            f"the preemption reopened instead of adopting"
        )
    if not adoption_ok:
        violations.append("attach() returned a different recorder")
    resets = erep["resets"]
    for stem in ("corro_changes_committed", "corro_changes_applied"):
        kinds = set((resets.get(stem) or {}).get("kinds", []))
        n_ev = (resets.get(stem) or {}).get("events", 0)
        if kinds != {"restart"} or n_ev != len(run.events):
            violations.append(
                f"counter {stem}: resets classified {sorted(kinds)} "
                f"x{n_ev}, want {{'restart'}} x{len(run.events)}"
            )
    if not erep["detectors_armed"]["wedge"]:
        violations.append("wedge detector never armed — harness failure")
    if not erep["ok"]:
        violations.extend(f"endurance: {b}" for b in erep["breaches"])

    ok = scen["ok"] and not violations
    return {
        "schema": ELASTIC_SCHEMA,
        "scenario": "soak_preempt",
        "kind": "preempt",
        "engine": "dense",
        "devices": devices,
        "bit_identical": scen["bit_identical"],
        "mismatches": scen["mismatches"],
        "violations": violations,
        "machinery": scen["machinery"],
        "reconcile": scen["reconcile"],
        "endurance": {
            "ok": erep["ok"],
            "resets": erep["resets"],
            "detectors_armed": erep["detectors_armed"],
            "breaches": erep["breaches"],
            "samples": erep["samples"],
        },
        "wall_s": scen["wall_s"],
        "seed": seed,
        "ok": bool(ok),
    }


def run_scenario(
    name: str, seed: int = 0, checkpoint_dir: str | None = None,
    series_path: str | None = None,
) -> dict:
    """Dispatch a catalog name to its runner."""
    if name.startswith("reshard_"):
        engine, pair = name[len("reshard_"):].rsplit("_", 1)
        d_from, d_to = (int(x) for x in pair.split("to"))
        return run_reshard_scenario(
            engine, d_from, d_to, seed=seed, checkpoint_dir=checkpoint_dir
        )
    if name == "preempt_dense_churn":
        return run_preempt_scenario(
            seed=seed, checkpoint_dir=checkpoint_dir
        )
    if name == "soak_preempt":
        if series_path is None:
            import tempfile

            with tempfile.TemporaryDirectory() as td:
                return run_soak_preempt_scenario(
                    td + "/series.jsonl", seed=seed
                )
        return run_soak_preempt_scenario(series_path, seed=seed)
    raise ValueError(
        f"unknown elastic scenario {name!r}; one of {scenario_names()}"
    )
