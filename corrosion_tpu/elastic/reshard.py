"""Live mesh resharding: checkpoint → re-place → resume, pinned
convergence-equivalent.

Production fleets autoscale: a run that started on D devices must be
able to continue on D′ ≠ D. All the ingredients already exist — the
shard_driver samples RNG at full shape and row-slices (bit-identity
across device counts by construction), every engine's scan driver
resumes from a carried state at an absolute round, and
``parallel/mesh.py`` keeps ONE spec source for placement and byte
prediction. This module composes them into the reshard flow:

1. run the prefix ``[0, split)`` sharded on ``mesh_from``;
2. gather the carried state to host at the chunk boundary (optionally
   round-tripping through the self-describing ``corro-checkpoint/1``
   disk format, sim/checkpoint.py);
3. re-place under the SAME ``*_specs`` builders on ``mesh_to`` and
   reconcile ``predicted_per_device_bytes`` against the live shards
   byte-exact BEFORE resuming (a placement that doesn't match its
   prediction is refused, not resumed);
4. resume the scan driver over the tail ``[split, rounds)``.

The contract is bit-identity, not tolerance: the resharded run's
remaining round curves (xshard byte keys excepted — the wire volume
legitimately depends on the mesh) and final CRDT state must equal the
uninterrupted same-seed run exactly. Any divergence is a bug
(elastic/report.py diffs them leaf-by-leaf; tests/test_elastic.py and
scripts/elastic_smoke.py pin it for (D→D′) ∈ {4→8, 8→4, 8→2, 1→8}).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from corrosion_tpu.parallel import mesh as mesh_mod
from corrosion_tpu.parallel import shard_driver
from corrosion_tpu.sim import checkpoint as checkpoint_mod


def mesh_dims(mesh) -> tuple:
    """The mesh's axis sizes (dcn outer first) — checkpoint-header form."""
    return tuple(int(mesh.shape[a]) for a in mesh.axis_names)


def virtual_mesh(d: int):
    """The standard virtual mesh at device count ``d`` — the 2-D WAN
    (dcn, ici) mesh for d >= 4, 1-D below (sim.benchlib.multichip_mesh),
    so reshard pairs like 4→8 exercise the multi-axis placement."""
    from corrosion_tpu.sim import benchlib

    return benchlib.multichip_mesh(d)


def schedule_slice(sched, start: int, stop: int):
    """The ``[start, stop)`` window of a Schedule — writes and every
    fault axis sliced, sample triplets kept absolute (the engines track
    visibility in absolute rounds). Mirrors sim.engine.simulate's own
    chunk slicing, so a prefix+tail pair replays the uninterrupted run
    exactly."""
    from corrosion_tpu.sim.engine import Schedule

    def cut(v):
        return None if v is None else v[start:stop]

    return Schedule(
        writes=sched.writes[start:stop],
        kill=cut(sched.kill),
        revive=cut(sched.revive),
        partition=cut(sched.partition),
        sample_writer=sched.sample_writer,
        sample_ver=sched.sample_ver,
        sample_round=sched.sample_round,
        loss=cut(sched.loss),
        probe_loss=cut(sched.probe_loss),
        wipe=cut(sched.wipe),
    )


def place_reconciled(host_tree, specs, mesh):
    """Place a host state pytree on ``mesh`` under ``specs`` and
    reconcile the byte arithmetic: ``predicted_per_device_bytes`` (from
    the spec tree) must equal every device's live
    ``per_device_state_bytes`` EXACTLY. Raises on any mismatch — a
    placement whose prediction is off must never be resumed into.
    Returns ``(placed_tree, reconcile_dict)``."""
    predicted = mesh_mod.predicted_per_device_bytes(host_tree, specs, mesh)
    placed = mesh_mod._put_specs(host_tree, specs, mesh)
    measured = shard_driver.per_device_state_bytes(placed)
    bad = {
        str(dev): int(b) for dev, b in measured.items() if b != predicted
    }
    if len(measured) != mesh.devices.size or bad:
        raise ValueError(
            f"reshard byte reconcile failed on {mesh_dims(mesh)}: "
            f"predicted {predicted} B/device, live mismatches {bad}, "
            f"{len(measured)}/{mesh.devices.size} devices reporting"
        )
    return placed, {
        "predicted_per_device_bytes": int(predicted),
        "devices": int(mesh.devices.size),
        "mesh": list(mesh_dims(mesh)),
        "ok": True,
    }


@dataclass
class ReshardRun:
    """One checkpoint→reshard→resume execution (engine-specific
    ``final``; the scenario layer compares it against the uninterrupted
    reference)."""

    engine: str
    mesh_from: tuple
    mesh_to: tuple
    split: int  # rounds before the reshard (epochs * e_len for sparse)
    final: object
    prefix_curves: dict
    tail_curves: dict
    reconcile: dict
    checkpoint: dict | None  # corro-checkpoint/1 header of the round-trip
    wall_s: dict = field(default_factory=dict)
    extra: dict = field(default_factory=dict)


def _ckpt_path(checkpoint_dir: str | None, name: str) -> str | None:
    if checkpoint_dir is None:
        return None
    os.makedirs(checkpoint_dir, exist_ok=True)
    return os.path.join(checkpoint_dir, name)


def run_dense_resharded(
    cfg,
    topo,
    sched,
    mesh_from,
    mesh_to,
    split_round: int,
    seed: int = 0,
    checkpoint_dir: str | None = None,
    fingerprint: str = "",
    telemetry=None,
) -> ReshardRun:
    """Dense engine: run ``[0, split_round)`` on ``mesh_from``,
    checkpoint/reshard, resume ``[split_round, rounds)`` on ``mesh_to``."""
    from corrosion_tpu.sim import engine

    if not (0 < split_round < sched.rounds):
        raise ValueError(
            f"split_round must be inside (0, {sched.rounds}), got "
            f"{split_round}"
        )
    wall: dict = {}
    t = time.perf_counter()
    state = mesh_mod.shard_cluster_state(
        engine.init_cluster(cfg, len(sched.sample_writer)), mesh_from
    )
    state, prefix_curves = shard_driver.simulate_sharded(
        cfg, topo, schedule_slice(sched, 0, split_round), mesh_from,
        seed=seed, state=state, telemetry=telemetry,
    )
    wall["prefix"] = time.perf_counter() - t

    t = time.perf_counter()
    host = jax.device_get(state)
    header = None
    path = _ckpt_path(checkpoint_dir, "dense_reshard.npz")
    if path is not None:
        checkpoint_mod.save_state(
            path, host, fingerprint=fingerprint,
            mesh_shape=mesh_dims(mesh_from),
        )
        host = checkpoint_mod.load_state(
            path, cfg, len(sched.sample_writer),
            expect_fingerprint=fingerprint,
        )
        header = checkpoint_mod.read_header(path)
    wall["checkpoint"] = time.perf_counter() - t

    t = time.perf_counter()
    placed, reconcile = place_reconciled(
        host, mesh_mod.cluster_state_specs(host, mesh_to), mesh_to
    )
    wall["reshard"] = time.perf_counter() - t

    t = time.perf_counter()
    final, tail_curves = shard_driver.simulate_sharded(
        cfg, topo, schedule_slice(sched, split_round, sched.rounds),
        mesh_to, seed=seed, state=placed, telemetry=telemetry,
    )
    wall["tail"] = time.perf_counter() - t
    return ReshardRun(
        engine="dense", mesh_from=mesh_dims(mesh_from),
        mesh_to=mesh_dims(mesh_to), split=split_round, final=final,
        prefix_curves=prefix_curves, tail_curves=tail_curves,
        reconcile=reconcile, checkpoint=header, wall_s=wall,
    )


def run_sparse_resharded(
    cfg,
    topo,
    sched,
    mesh_from,
    mesh_to,
    split_epoch: int,
    seed: int = 0,
    checkpoint_dir: str | None = None,
    fingerprint: str = "",
    telemetry=None,
) -> ReshardRun:
    """Sparse (any-node-writes) engine: epochs are its chunk boundaries.
    Run ``split_epoch`` epochs on ``mesh_from``, persist the resume
    point WITH the schedule's fault axes (the resume-asymmetry fix in
    sim/checkpoint.py), reshard, and resume the remaining epochs on
    ``mesh_to`` against the full original schedule."""
    wall: dict = {}
    t = time.perf_counter()
    *_pre, prefix_curves, info = shard_driver.simulate_sparse_sharded(
        cfg, topo, sched, mesh_from, seed=seed,
        stop_after_epoch=split_epoch - 1, telemetry=telemetry,
    )
    resume = info["resume"]
    wall["prefix"] = time.perf_counter() - t

    t = time.perf_counter()
    host = {
        "sstate": jax.device_get(resume["sstate"]),
        "swim": jax.device_get(resume["swim"]),
        "vis_round": jax.device_get(resume["vis_round"]),
        "planner": resume["planner"],
        "next_epoch": int(resume["next_epoch"]),
    }
    header = None
    path = _ckpt_path(checkpoint_dir, "sparse_reshard.npz")
    if path is not None:
        checkpoint_mod.save_sparse_resume(
            path, host, schedule=sched, fingerprint=fingerprint,
            mesh_shape=mesh_dims(mesh_from),
        )
        host = checkpoint_mod.load_sparse_resume(
            path, cfg, len(sched.sample_writer),
            expect_fingerprint=fingerprint,
        )
        # The persisted fault axes must agree with (or restore) the
        # schedule the resumed run replays — the asymmetry this fixes.
        sched = checkpoint_mod.attach_resume_faults(sched, host)
        header = checkpoint_mod.read_header(path)
    wall["checkpoint"] = time.perf_counter() - t

    t = time.perf_counter()
    node = shard_driver.node_spec_entry(mesh_to)
    tree = (host["sstate"], host["swim"], host["vis_round"])
    specs = (
        mesh_mod.sparse_state_specs(host["sstate"], mesh_to),
        mesh_mod.node_major_specs(host["swim"], mesh_to),
        P(None, node),
    )
    placed, reconcile = place_reconciled(tree, specs, mesh_to)
    resume2 = {
        "sstate": placed[0],
        "swim": placed[1],
        "vis_round": placed[2],
        "planner": host["planner"],
        "next_epoch": int(host["next_epoch"]),
    }
    wall["reshard"] = time.perf_counter() - t

    t = time.perf_counter()
    sstate, swim_state, vis_round, tail_curves, info2 = (
        shard_driver.simulate_sparse_sharded(
            cfg, topo, sched, mesh_to, seed=seed, resume=resume2,
            telemetry=telemetry,
        )
    )
    wall["tail"] = time.perf_counter() - t
    e_len = getattr(cfg, "epoch_rounds", None) or getattr(
        cfg.sparse, "epoch_rounds"
    )
    return ReshardRun(
        engine="sparse", mesh_from=mesh_dims(mesh_from),
        mesh_to=mesh_dims(mesh_to), split=split_epoch * int(e_len),
        final=(sstate, swim_state, vis_round),
        prefix_curves=prefix_curves, tail_curves=tail_curves,
        reconcile=reconcile, checkpoint=header, wall_s=wall,
        extra={"split_epoch": split_epoch, "epochs": info2["epochs"]},
    )


def run_chunks_resharded(
    ccfg,
    origin,
    last_seq,
    rounds: int,
    mesh_from,
    mesh_to,
    split_round: int,
    seed: int = 0,
    checkpoint_dir: str | None = None,
    fingerprint: str = "",
    telemetry=None,
) -> ReshardRun:
    """Seq-chunk engine: coverage state + the visibility latch carry
    across the reshard; the resumed call folds ``start_round`` into its
    per-round RNG keys (the sim/chunk_engine.py resume seam)."""
    from corrosion_tpu.ops import chunks as chunk_ops

    wall: dict = {}
    t = time.perf_counter()
    state, m1 = shard_driver.simulate_chunks_sharded(
        ccfg, origin, last_seq, split_round, mesh_from, seed=seed,
        telemetry=telemetry,
    )
    wall["prefix"] = time.perf_counter() - t

    t = time.perf_counter()
    host = jax.device_get((state, m1["vis"]))
    header = None
    path = _ckpt_path(checkpoint_dir, "chunk_reshard.npz")
    if path is not None:
        checkpoint_mod.save_tree(
            path, host, fingerprint=fingerprint,
            mesh_shape=mesh_dims(mesh_from), round_index=split_round,
        )
        template = jax.device_get((
            chunk_ops.init_chunks(
                ccfg, np.asarray(origin, np.int32),
                np.asarray(last_seq, np.int32),
            ),
            np.full((ccfg.n_nodes, ccfg.n_streams), -1, np.int32),
        ))
        host = checkpoint_mod.load_tree(
            path, template, expect_fingerprint=fingerprint
        )
        header = checkpoint_mod.read_header(path)
    wall["checkpoint"] = time.perf_counter() - t

    t = time.perf_counter()
    node = shard_driver.node_spec_entry(mesh_to)
    specs = (
        mesh_mod.node_major_specs(host[0], mesh_to),
        P(node, None),
    )
    placed, reconcile = place_reconciled(host, specs, mesh_to)
    wall["reshard"] = time.perf_counter() - t

    t = time.perf_counter()
    final, m2 = shard_driver.simulate_chunks_sharded(
        ccfg, origin, last_seq, rounds - split_round, mesh_to, seed=seed,
        state=placed[0], vis=placed[1], start_round=split_round,
        telemetry=telemetry,
    )
    wall["tail"] = time.perf_counter() - t
    return ReshardRun(
        engine="chunk", mesh_from=mesh_dims(mesh_from),
        mesh_to=mesh_dims(mesh_to), split=split_round,
        final=(final, m2["vis"]),
        prefix_curves=m1["curves"], tail_curves=m2["curves"],
        reconcile=reconcile, checkpoint=header, wall_s=wall,
        extra={"metrics": {
            k: v for k, v in m2.items() if k not in ("curves", "vis")
        }},
    )


def run_mixed_resharded(
    cfg,
    ccfg,
    topo,
    sched,
    streams,
    mesh_from,
    mesh_to,
    split_round: int,
    seed: int = 0,
    checkpoint_dir: str | None = None,
    fingerprint: str = "",
    telemetry=None,
) -> ReshardRun:
    """Mixed chunk+version engine: the carried MixedState's ``round``
    anchors the tail in absolute rounds (the sim/mixed_engine.py resume
    seam — RNG keys and the stream commit matrix both offset by it)."""
    from corrosion_tpu.sim import mixed_engine

    wall: dict = {}
    t = time.perf_counter()
    state, prefix_curves = shard_driver.simulate_mixed_sharded(
        cfg, ccfg, topo, schedule_slice(sched, 0, split_round), streams,
        mesh_from, seed=seed, telemetry=telemetry,
    )
    wall["prefix"] = time.perf_counter() - t

    t = time.perf_counter()
    host = jax.device_get(state)
    header = None
    path = _ckpt_path(checkpoint_dir, "mixed_reshard.npz")
    if path is not None:
        checkpoint_mod.save_tree(
            path, host, fingerprint=fingerprint,
            mesh_shape=mesh_dims(mesh_from), round_index=split_round,
        )
        template = jax.device_get(mixed_engine.init_mixed_state(
            cfg, ccfg, topo, sched, streams
        ))
        host = checkpoint_mod.load_tree(
            path, template, expect_fingerprint=fingerprint
        )
        header = checkpoint_mod.read_header(path)
    wall["checkpoint"] = time.perf_counter() - t

    t = time.perf_counter()
    placed, reconcile = place_reconciled(
        host, mesh_mod.mixed_state_specs(host, mesh_to), mesh_to
    )
    wall["reshard"] = time.perf_counter() - t

    t = time.perf_counter()
    final, tail_curves = shard_driver.simulate_mixed_sharded(
        cfg, ccfg, topo, schedule_slice(sched, split_round, sched.rounds),
        streams, mesh_to, seed=seed, state=placed, telemetry=telemetry,
    )
    wall["tail"] = time.perf_counter() - t
    return ReshardRun(
        engine="mixed", mesh_from=mesh_dims(mesh_from),
        mesh_to=mesh_dims(mesh_to), split=split_round, final=final,
        prefix_curves=prefix_curves, tail_curves=tail_curves,
        reconcile=reconcile, checkpoint=header, wall_s=wall,
    )
