"""Elastic survival plane: live mesh resharding and device-shard
preemption, pinned convergence-equivalent.

- :mod:`corrosion_tpu.elastic.reshard` — checkpoint → re-place on a
  different virtual mesh → resume, with byte-exact
  ``predicted_per_device_bytes`` reconcile before every resume.
- :mod:`corrosion_tpu.elastic.preempt` — hard device-shard kill
  (``Agent.abort`` semantics) + checkpoint/replay recovery with the
  machinery-fired rule.
- :mod:`corrosion_tpu.elastic.scenarios` — the named drill catalog
  (reshard matrix, preempt_dense_churn, soak_preempt).
- :mod:`corrosion_tpu.elastic.report` — bit-exact diff helpers and the
  bench_budget.json ``elastic`` gate.

Everything runs on the virtual CPU mesh (``JAX_PLATFORMS=cpu`` with
``--xla_force_host_platform_device_count=8``); the convergence contract
is bit-identity, never tolerance. See docs/SCALING.md "Elastic ops".
"""

from corrosion_tpu.elastic.preempt import (
    PreemptRun,
    RecoveryCounters,
    poison_lost_shard,
    run_dense_preempted,
)
from corrosion_tpu.elastic.report import (
    ELASTIC_SCHEMA,
    check_elastic_budget,
    diff_curves,
    diff_trees,
)
from corrosion_tpu.elastic.reshard import (
    ReshardRun,
    place_reconciled,
    run_chunks_resharded,
    run_dense_resharded,
    run_mixed_resharded,
    run_sparse_resharded,
    schedule_slice,
    virtual_mesh,
)
from corrosion_tpu.elastic.scenarios import (
    RESHARD_MATRIX,
    run_preempt_scenario,
    run_reshard_scenario,
    run_scenario,
    run_soak_preempt_scenario,
    scenario_names,
)

__all__ = [
    "ELASTIC_SCHEMA",
    "PreemptRun",
    "RecoveryCounters",
    "ReshardRun",
    "RESHARD_MATRIX",
    "check_elastic_budget",
    "diff_curves",
    "diff_trees",
    "place_reconciled",
    "poison_lost_shard",
    "run_chunks_resharded",
    "run_dense_preempted",
    "run_dense_resharded",
    "run_mixed_resharded",
    "run_preempt_scenario",
    "run_reshard_scenario",
    "run_scenario",
    "run_soak_preempt_scenario",
    "run_sparse_resharded",
    "schedule_slice",
    "scenario_names",
    "virtual_mesh",
]
