"""The three standing fidelity scenarios (docs/FIDELITY.md).

- :func:`steady_load`: a constant-rate write stream across every live
  writer — the baseline mixed-mode comparison (live cluster vs kernel
  replay, calibrated and uncalibrated).
- :func:`burst_drain`: every write packed into one instant, then an idle
  drain — the shape that stresses round bucketing hardest (all events in
  one ``round_ms`` window; the zero-duration trace case
  ``schedule_from_trace`` must bucket into a valid 1-round schedule).
- :func:`dcn_partition`: the DCN-scale scenario the 2-D mesh makes
  natural (ROADMAP item 5's widened-chaos clause): a synthetic-WAN
  kernel cluster (geo ring classes → ring-occupancy model), one whole
  region group partitioned then healed. No loopback cluster can realize
  WAN rings, so this scenario is kernel-vs-kernel — calibrated axes vs
  none under the identical partition plan — cross-checked against the
  chaos plane's post-heal invariant suite (``sim.invariants.run_dense``
  must pass the plan standalone, pinning the scenario inside the chaos
  plane's validated envelope).

``full_report`` is the standing lane's measurement
(``scripts/fidelity_smoke.py`` and the ``fidelity`` CI job).
"""

from __future__ import annotations

import os

import numpy as np

from corrosion_tpu.fidelity.calibrate import (
    RoundModel,
    from_ring_occupancy,
    trace_fingerprint,
)
from corrosion_tpu.fidelity.compare import compare_live_kernel

# Mixed-mode scenario shapes (CI-feasible; the CLI scales them up).
STEADY_WRITES = 24
STEADY_RATE_HZ = 12.0
BURST_WRITES = 24

# DCN scenario shape — the chaos plane's standard dense scenario shape
# (sim/invariants.py) so the invariant cross-check and the fidelity run
# agree on geography.
DCN_NODES = 48
DCN_REGIONS = 4
DCN_ROUNDS = 64


def steady_arrivals(
    writes: int = STEADY_WRITES, rate_hz: float = STEADY_RATE_HZ,
    writers: int = 3,
) -> list:
    """Open-loop constant-rate grid, round-robin over writers."""
    return [
        (i / rate_hz, i % writers) for i in range(writes)
    ]


def burst_arrivals(writes: int = BURST_WRITES) -> list:
    """Every write scheduled at t=0 on ONE writer (back-to-back commits,
    the same regime the apply-rate calibration train measures), then
    nothing: the drain is pure propagation. A multi-writer burst would
    make every receiver also a bursting writer, so its store writer
    would be busy with its own commits — a contention scenario, not a
    dissemination one."""
    return [(0.0, 0)] * writes


async def steady_load(
    data_dir: str,
    writes: int = STEADY_WRITES,
    rate_hz: float = STEADY_RATE_HZ,
    n_agents: int = 3,
    model: RoundModel | None = None,
    seed: int = 0,
    progress=None,
) -> dict:
    rep = await compare_live_kernel(
        os.path.join(data_dir, "steady"),
        steady_arrivals(writes, rate_hz, writers=n_agents),
        n_agents=n_agents, model=model, seed=seed, progress=progress,
    )
    rep["scenario"] = "steady"
    return rep


async def burst_drain(
    data_dir: str,
    writes: int = BURST_WRITES,
    n_agents: int = 3,
    model: RoundModel | None = None,
    seed: int = 0,
    progress=None,
) -> dict:
    rep = await compare_live_kernel(
        os.path.join(data_dir, "burst"),
        burst_arrivals(writes),
        n_agents=n_agents, model=model, seed=seed, progress=progress,
    )
    rep["scenario"] = "burst"
    return rep


# ---------------------------------------------------------------------------
# DCN-scale partition scenario (kernel-side, invariant-cross-checked).


def wan_ring_model(flush_ms: float = 500.0) -> RoundModel:
    """The synthetic-WAN round model: geo ring classes (the kernel's
    ``region_rtt="geo"`` circle geography at the DCN scenario shape)
    turned into one-hot ring occupancy — members.rs:33 ring semantics as
    a calibration input."""
    from corrosion_tpu.fidelity.calibrate import RING_REPR_MS
    from corrosion_tpu.ops import gossip

    topo = gossip.make_topology(
        [DCN_NODES // DCN_REGIONS] * DCN_REGIONS,
        [0], region_rtt="geo",
    )
    rings = np.asarray(topo.region_rtt)  # [R, R] ring classes 0-5
    occ = np.zeros(
        (DCN_REGIONS, DCN_REGIONS, len(RING_REPR_MS)), np.int64
    )
    for i in range(DCN_REGIONS):
        for j in range(DCN_REGIONS):
            occ[i, j, int(rings[i, j])] = 1
    return from_ring_occupancy(
        occ, flush_ms=flush_ms,
        provenance={
            "source": "geo-ring-occupancy",
            "nodes": DCN_NODES,
            "regions": DCN_REGIONS,
        },
    )


def dcn_partition(
    rounds: int = DCN_ROUNDS, seed: int = 0, progress=None
) -> dict:
    """Partition one whole region group then heal, with and without the
    WAN ring model's calibrated axes, cross-checked against the chaos
    invariant suite. Returns the scenario report block."""
    from corrosion_tpu.models.baselines import _cfg
    from corrosion_tpu.sim import invariants as inv
    from corrosion_tpu.sim.engine import Schedule, simulate
    from corrosion_tpu.sim.faults import Fault, FaultPlan, apply_plan
    from corrosion_tpu.sim.health import recovery_after_heal

    def note(msg):
        if progress is not None:
            progress.write(f"[fidelity dcn] {msg}\n")
            progress.flush()

    model = wan_ring_model()
    plan = FaultPlan(rounds, (
        Fault("partition", rounds // 6, rounds // 2, a=(0,)),
    ), name="dcn-partition-heal")

    # Cross-check: the bare plan must pass the chaos plane's post-heal
    # invariant suite on the standard dense scenario — the calibrated
    # run below then only ADDS the model's ambient-loss axes on top of
    # an envelope the invariant suite has validated.
    note("invariant cross-check (chaos suite, dense)")
    inv_rep = inv.run_dense(plan, seed=seed)

    writers = list(range(4))
    cfg, topo = _cfg(
        DCN_NODES, writers=writers,
        regions=[DCN_NODES // DCN_REGIONS] * DCN_REGIONS,
        region_rtt="geo", sync_interval=5, n_cells=0,
    )
    rng = np.random.default_rng(seed)
    w_stop = max(plan.heal_round + 2, rounds // 2)
    writes = np.zeros((rounds, len(writers)), np.uint32)
    writes[:w_stop] = (
        rng.random((w_stop, len(writers))) < 0.25
    ).astype(np.uint32)
    writes[0, :] = 1

    def run(with_model: bool) -> dict:
        sched = Schedule(writes=writes.copy()).make_samples(64)
        sched = apply_plan(sched, plan, DCN_NODES, DCN_REGIONS)
        if with_model:
            sched = model.apply(sched, n_nodes=DCN_NODES)
        final, curves = simulate(cfg, topo, sched, seed=seed)
        rec = recovery_after_heal(
            curves, plan.heal_round, round_ms=model.round_ms
        )
        vis = np.asarray(final.vis_round)
        seen = vis >= 0
        lat = (
            vis.astype(np.float64)
            - sched.sample_round[:, None].astype(np.float64)
        )[seen]
        return {
            "recovered_round": rec["recovered_round"],
            "recovery_rounds": rec["recovery_rounds"],
            "unseen": int((~seen).sum()),
            "vis_p99_rounds": (
                round(float(np.percentile(lat, 99)), 2) if lat.size else None
            ),
            "need_last": float(np.asarray(curves["need"])[-1]),
        }

    note("calibrated run (partition + model axes)")
    cal = run(with_model=True)
    note("uncalibrated run (partition only)")
    uncal = run(with_model=False)
    recovery_delta = (
        None
        if cal["recovery_rounds"] is None or uncal["recovery_rounds"] is None
        else cal["recovery_rounds"] - uncal["recovery_rounds"]
    )
    return {
        "scenario": "dcn",
        "model": model.to_dict(),
        "plan": plan.to_dict(),
        "invariants_ok": bool(inv_rep.ok),
        "invariant_violations": list(inv_rep.violations),
        "calibrated": cal,
        "uncalibrated": uncal,
        # The WAN model injects ambient miss, so calibrated recovery may
        # lag the ideal run — the gate ceilings bound by how much.
        "recovery_delta_rounds": recovery_delta,
        "both_recovered": (
            cal["recovered_round"] is not None
            and uncal["recovered_round"] is not None
        ),
    }


# ---------------------------------------------------------------------------
# The standing lane's measurement.


async def full_report(
    data_dir: str,
    scenario: str = "ci_smoke",
    steady_writes: int = STEADY_WRITES,
    burst_writes: int = BURST_WRITES,
    n_agents: int = 3,
    dcn_rounds: int = DCN_ROUNDS,
    seed: int = 0,
    progress=None,
) -> dict:
    """Run all three standing scenarios and assemble the self-describing
    fidelity report (``fidelity.report.emit_fidelity_report`` asserts
    its provenance)."""
    from corrosion_tpu.fidelity.report import fidelity_context

    steady = await steady_load(
        data_dir, writes=steady_writes, n_agents=n_agents, seed=seed,
        progress=progress,
    )
    burst = await burst_drain(
        data_dir, writes=burst_writes, n_agents=n_agents, seed=seed,
        progress=progress,
    )
    dcn = dcn_partition(rounds=dcn_rounds, seed=seed, progress=progress)
    # The report-level fingerprint ties the gate to the workloads that
    # produced it (each scenario block carries its own too).
    fp = trace_fingerprint([
        (0, steady["trace_fingerprint"], 0),
        (1, burst["trace_fingerprint"], 1),
    ])
    return {
        **fidelity_context(
            scenario, n_agents, fp,
            steady_writes, burst_writes, dcn_rounds, seed,
        ),
        "scenarios": {
            "steady": steady,
            "burst": burst,
            "dcn": dcn,
        },
    }
