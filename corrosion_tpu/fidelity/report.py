"""Fidelity-plane report emit path + the ``fidelity`` budget gate.

Every divergence report funnels through the ONE self-describing emit
path (``telemetry.check_bench_invariants``, the PR 6 rule): platform,
nodes, device_count, config fingerprint — plus ``scenario`` and
``trace_fingerprint`` for this report class — are asserted at the emit
site, so a fidelity verdict can no more be published without saying
which workload produced it than a kernel bench can be published without
its platform.

``check_fidelity_budget`` mirrors the serving gate's shape for the
fidelity surface: dimension mismatches (platform / scenario) are
breaches, divergence ceilings get the budget's tolerance multiplier, and
two checks are absolute — the **calibrated-beats-uncalibrated CDF
ordering** (the subsystem's reason to exist; a tolerance-scaled version
would gate nothing) and the DCN scenario's **chaos-invariant
cross-check**.
"""

from __future__ import annotations

from corrosion_tpu.sim import benchlib, telemetry

# Dimensions that must match the budget exactly.
FIDELITY_DIMS = ("platform", "scenario")

# Provenance this report class requires beyond the base four.
FIDELITY_PROVENANCE = ("scenario", "trace_fingerprint")


def emit_fidelity_report(report: dict) -> dict:
    """The fidelity plane's emit site: assert self-description (base
    provenance + scenario + trace fingerprint) and return the report
    unchanged."""
    return telemetry.check_bench_invariants(
        report, extra_provenance=FIDELITY_PROVENANCE
    )


def fidelity_context(
    scenario: str, nodes: int, trace_fp: str, *fingerprint_parts
) -> dict:
    """Provenance block for a fidelity report: ``nodes`` is the live
    agent cluster size, ``trace_fingerprint`` ties the verdict to the
    recorded workload, the rest comes from the shared benchlib context
    (platform, device_count, config fingerprint)."""
    return {
        **benchlib.bench_context(scenario, nodes, *fingerprint_parts),
        "scenario": scenario,
        "nodes": nodes,
        "trace_fingerprint": trace_fp,
    }


_get = benchlib.get_path


def check_fidelity_budget(
    measured: dict, budget: dict
) -> tuple[bool, list[str]]:
    """Gate a fidelity report against the ``fidelity`` entry of
    bench_budget.json. Returns ``(ok, breaches)``.

    Budget keys:

    - ``tolerance``: multiplier on every ``ceilings`` value.
    - dimension keys (``FIDELITY_DIMS``): must equal the measurement.
    - ``ceilings``: dotted-path -> max value (e.g.
      ``"scenarios.steady.calibrated.cdf_distance"``); a missing
      measurement is a breach (a silently vanished scenario is how
      regressions hide).
    - ``require_calibrated_closer`` (default True): on every mixed-mode
      scenario block, the calibrated replay's CDF distance must be
      STRICTLY below the uncalibrated one's — never tolerance-scaled.
    - ``require_invariants_ok`` (default True): every scenario block
      carrying an ``invariants_ok`` fact (the DCN cross-check) must
      report it true — never tolerance-scaled.
    - ``unseen_max`` (default 0): total never-became-visible pairs
      across live runs and calibrated replays (non-convergence is a
      correctness question, not a tolerance one).
    """
    tol = float(budget.get("tolerance", benchlib.DEFAULT_TOLERANCE))
    breaches: list[str] = []
    for dim in FIDELITY_DIMS:
        if dim in budget and measured.get(dim) != budget[dim]:
            breaches.append(
                f"{dim}: measured at {measured.get(dim)!r} but the budget "
                f"was refreshed at {budget[dim]!r} — rerun with --update"
            )
    for path, limit in budget.get("ceilings", {}).items():
        got = _get(measured, path)
        if got is None:
            breaches.append(f"{path}: missing from measurement")
        elif float(got) > float(limit) * tol:
            breaches.append(
                f"{path}: {float(got):.4f} > budget {float(limit):.4f} "
                f"x{tol:g}"
            )
    scen = measured.get("scenarios", {})
    if budget.get("require_calibrated_closer", True):
        for name, block in sorted(scen.items()):
            if "calibrated_closer" not in block:
                continue  # kernel-vs-kernel scenarios have no live CDF
            if not block["calibrated_closer"]:
                cal = _get(block, "calibrated.cdf_distance")
                unc = _get(block, "uncalibrated.cdf_distance")
                breaches.append(
                    f"scenarios.{name}: calibrated replay is NOT strictly "
                    f"closer to the live CDF ({cal} vs uncalibrated {unc}) "
                    f"— the round-length calibration buys nothing here"
                )
    if budget.get("require_invariants_ok", True):
        for name, block in sorted(scen.items()):
            if "invariants_ok" in block and not block["invariants_ok"]:
                breaches.append(
                    f"scenarios.{name}: chaos invariant cross-check failed: "
                    f"{block.get('invariant_violations')}"
                )
    unseen_max = int(budget.get("unseen_max", 0))
    unseen = sum(
        int(v)
        for name, block in scen.items()
        for v in (
            _get(block, "live.unseen"),
            _get(block, "calibrated.unseen"),
        )
        if v is not None
    )
    if unseen > unseen_max:
        breaches.append(
            f"unseen pairs: {unseen} > {unseen_max} — some writes never "
            f"became visible (live or calibrated replay did not converge)"
        )
    return not breaches, breaches
