"""Fidelity plane: calibrated round-length model + mixed-mode
live-vs-kernel validation (docs/FIDELITY.md).

Every kernel artifact rests on the identification "one round-synchronous
simulator step ≙ 500 ms of the reference's event-driven reality"
(SURVEY.md hard part (b)). This package validates and replaces that
identification with a measured one:

- ``calibrate``: the :class:`RoundModel` — a calibrated ``round_ms``
  derived from the broadcast flush tick + measured probe-RTT
  distributions (raw samples or members.rs:33 ring occupancy), plus
  per-region-pair delivery-miss probabilities and SWIM probe-plane loss
  from probe timeout tails. Compiles into the EXISTING chaos-plane
  Schedule axes (``sim.faults.axes_from_rates`` → ``apply_plan``): zero
  new traced code, and the identity model keeps engine traces
  bit-identical.
- ``compare``: the mixed-mode harness — one recorded write workload run
  through BOTH a live loopback agent cluster (traced via
  ``sim.trace.Trace``, per-write visibility sampled from NDJSON
  subscriptions) and the kernel replay, calibrated vs uncalibrated, with
  the divergence quantified in the existing delivery-latency bucket
  space.
- ``scenarios``: the three standing scenarios (steady write load, write
  burst + idle drain, DCN-scale partition-and-heal cross-checked against
  the chaos invariant suite) behind the ``fidelity`` CLI group.
- ``report``: the self-describing emit path
  (``telemetry.check_bench_invariants`` + ``trace_fingerprint``
  provenance) and the ``fidelity`` budget gate used by the fidelity CI
  job — the calibrated-beats-uncalibrated ordering is never
  tolerance-scaled.

``calibrate`` and ``report`` are host-side numpy/stdlib logic; the
heavy halves (live agents, engine runs) load lazily inside
``compare``/``scenarios`` functions. (Like every ``corrosion_tpu.sim``
import, loading the package pays the jax import — see the obs CLI
note in cli.py.)
"""

from corrosion_tpu.fidelity.calibrate import (
    MODEL_SCHEMA,
    REFERENCE_ROUND_MS,
    RoundModel,
    derive_model,
    from_characterization,
    from_ring_occupancy,
    identity_model,
    trace_fingerprint,
)
from corrosion_tpu.fidelity.report import (
    check_fidelity_budget,
    emit_fidelity_report,
    fidelity_context,
)

__all__ = [
    "MODEL_SCHEMA",
    "REFERENCE_ROUND_MS",
    "RoundModel",
    "check_fidelity_budget",
    "derive_model",
    "emit_fidelity_report",
    "fidelity_context",
    "from_characterization",
    "from_ring_occupancy",
    "identity_model",
    "trace_fingerprint",
]
