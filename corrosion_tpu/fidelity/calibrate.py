"""The calibrated round-length model (``corro-round-model/1``).

Every kernel number this repo publishes rests on one identification: one
round-synchronous simulator step ≙ ``round_ms`` of the reference's
event-driven, jitter-timed reality (500 ms, the broadcast flush tick,
broadcast/mod.rs:373). SURVEY.md's open "hard part (b)" is that nothing
ever VALIDATED that identification. This module derives the
identification from measured signals instead of assuming it:

- **the broadcast flush tick** (the live agent's configured
  ``broadcast_interval`` — the cadence at which committed writes actually
  leave the node);
- **per-region-pair RTT distributions**, either as raw probe samples
  (live calibration actively pings through the real SWIM UDP plane, like
  ``scripts/transport_characterization.py``) or as **ring occupancy** —
  sample counts per the reference's RTT ring buckets
  (members.rs:33 edges, ``agent/membership.RING_BUCKETS_MS``) — so host
  ``MemberState.rtt``/``rtt_ring`` state is a calibration input;
- **probe timeout tails**, which become the SWIM probe-plane loss rate.

The derived :class:`RoundModel` maps wall-clock asynchrony into
kernel-consumable data:

- ``round_ms``: the measured **delivery-pipeline tick** — broadcast
  flush tick + receiver-side apply/ingest batching tick
  (``AgentConfig.ingest_linger``, the handle_changes batching the
  reference also pays, agent.rs:2450-2518) + one-way p50 transit — the
  calibrated round length ``schedule_from_trace`` should bucket at
  instead of a hardcoded 500. One kernel round aggregates
  commit→flush→transit→apply, so the calibrated round must cover that
  whole pipeline, not the flush alone;
- ``vis_offset_rounds``: the continuous→round-synchronous correction. A
  write commits uniformly WITHIN a round, and "delivered in round r"
  means visible at r's closing flush — so a kernel latency of ``k``
  rounds corresponds to ``(k + 0.5) * round_ms`` of expected wall
  clock. The offset applies SYMMETRICALLY to calibrated and
  uncalibrated replays in the comparison (each with its own round
  length), so it can never favor one side;
- ``pair_miss[receiver][source]``: the probability a message's one-way
  latency straddles a round boundary (commit uniform in the round, so
  ``P(miss) = E[min(one_way / round_ms, 1)]``) and slips past this
  round's flush — the kernel's loss-then-recover axes model exactly
  that (a lost broadcast is recovered by rebroadcast/anti-entropy, i.e.
  delivered later);
- ``probe_loss``: the fraction of SWIM probes that exceeded the probe
  timeout.

Critically the model compiles into the EXISTING chaos-plane axes
(:func:`corrosion_tpu.sim.faults.axes_from_rates` →
``Schedule.loss``/``probe_loss``): calibration is data flowing through
already-tested static-skip machinery, and zero new traced code enters
the engines. The identity model (all rates ~0) compiles to absent axes,
so calibrated-but-lossless runs trace bit-identically to uncalibrated
ones. Everything here is host-side stdlib + numpy.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field

import numpy as np

from corrosion_tpu.agent.membership import RING_BUCKETS_MS, rtt_ring
from corrosion_tpu.sim.faults import CompiledFaults, axes_from_rates

MODEL_SCHEMA = "corro-round-model/1"

# Representative RTT per ring bucket: the bucket midpoint for the five
# bounded buckets, and the last reference edge (300 ms) for the
# open-ended top ring (members.rs:33 stops enumerating there).
RING_REPR_MS = tuple(
    (lo + hi) / 2.0
    for lo, hi in zip((0.0,) + RING_BUCKETS_MS[:-1], RING_BUCKETS_MS[:-1])
) + (RING_BUCKETS_MS[-1],)

# The reference's flush tick — the uncalibrated identification every
# pre-fidelity artifact used (sim/engine.py round model docstring).
REFERENCE_ROUND_MS = 500.0


def _percentile(xs, q: float) -> float:
    return float(np.percentile(np.asarray(xs, np.float64), q))


def trace_fingerprint(events) -> str:
    """Stable short hash of a trace's (t, actor, version) events — the
    provenance field tying a divergence report to the workload that
    produced it."""
    h = hashlib.sha256()
    for t, a, v in sorted(events):
        h.update(f"{t}:{a}:{v}".encode())
        h.update(b"\x00")
    return h.hexdigest()[:16]


@dataclass
class RoundModel:
    """Calibrated round-length model. ``pair_*`` matrices are
    [receiver_region][source_region]; region 0 alone for a loopback
    cluster."""

    round_ms: float
    flush_ms: float
    regions: int
    pair_rtt_p50_ms: list = field(default_factory=list)  # [R][R]
    pair_rtt_p99_ms: list = field(default_factory=list)  # [R][R]
    ring_occupancy: list = field(default_factory=list)  # [R][R][rings]
    pair_miss: list = field(default_factory=list)  # [R][R] in [0, 1]
    probe_loss: float = 0.0
    apply_ms: float = 0.0  # receiver-side ingest/apply batching tick
    vis_offset_rounds: float = 0.5  # round→wall discretization offset
    # Measured receiver apply drain rate (applies/s through the store
    # writer, sampled on a back-to-back calibration train DISJOINT from
    # any compared workload). 0 = unmeasured/unbounded: no backlog term.
    apply_rate_per_s: float = 0.0
    provenance: dict = field(default_factory=dict)

    def __post_init__(self):
        if not self.round_ms > 0.0:
            raise ValueError(f"round_ms must be positive: {self.round_ms}")
        if self.regions < 1:
            raise ValueError(f"regions must be >= 1: {self.regions}")
        for name in ("pair_rtt_p50_ms", "pair_rtt_p99_ms", "pair_miss"):
            m = getattr(self, name)
            if len(m) != self.regions or any(
                len(row) != self.regions for row in m
            ):
                raise ValueError(
                    f"{name} must be [{self.regions}][{self.regions}]"
                )
        if not 0.0 <= self.probe_loss <= 1.0:
            raise ValueError(f"probe_loss must be in [0, 1]: {self.probe_loss}")
        if self.apply_ms < 0.0:
            raise ValueError(f"apply_ms must be >= 0: {self.apply_ms}")
        if self.apply_rate_per_s < 0.0:
            raise ValueError(
                f"apply_rate_per_s must be >= 0: {self.apply_rate_per_s}"
            )
        if not 0.0 <= self.vis_offset_rounds <= 1.0:
            raise ValueError(
                f"vis_offset_rounds must be in [0, 1]: {self.vis_offset_rounds}"
            )

    # -- derived views -------------------------------------------------------

    @property
    def is_identity(self) -> bool:
        """True when compiling attaches NO fault axes (the static-skip
        fast path: the engines trace bit-identically to no model)."""
        return (
            self.loss_by_region().max() <= 1e-9
            and self.probe_loss <= 1e-9
            and self.apply_rate_per_s <= 0.0
        )

    def loss_by_region(self) -> np.ndarray:
        """f32[R] receiver-region delivery-miss probability — the mean
        over source regions of ``pair_miss`` (the Schedule loss axis is
        per receiver region; sources are sampled ~uniformly by the
        broadcast plane)."""
        return np.asarray(self.pair_miss, np.float32).mean(axis=1)

    def compile_axes(self, rounds: int) -> CompiledFaults:
        """Lower to the chaos plane's per-round arrays
        (``sim.faults.axes_from_rates``). Bit-identical across calls for
        equal inputs; the identity model compiles to all-``None`` axes."""
        return axes_from_rates(
            rounds,
            loss_by_region=self.loss_by_region(),
            probe_loss=self.probe_loss,
        )

    def apply(self, schedule, n_nodes: int):
        """Merge the compiled axes into a ``sim.engine.Schedule`` via the
        chaos plane's ``apply_plan`` (the one tested merge path). The
        schedule's region count must equal the model's."""
        from corrosion_tpu.sim.faults import apply_plan

        return apply_plan(
            schedule, self.compile_axes(schedule.rounds),
            n_nodes=n_nodes, n_regions=self.regions,
        )

    def defer_schedule(self, schedule):
        """Apply the measured dissemination capacity MECHANICALLY: each
        round admits at most ``apply_rate_per_s * round_ms`` writes into
        the kernel schedule; a burst's overflow carries to later rounds
        in FIFO commit order (round-robin across same-round writers).

        The schedule's SAMPLES are untouched — they keep the true commit
        rounds — so replay visibility latencies measure commit→visible
        including the modeled backlog delay, exactly as the live
        measurement does. Per-writer version order is preserved (the
        ``schedule_from_trace`` count-per-bucket encoding stays valid).
        Deterministic; a no-op when the rate is unmeasured (0) or the
        schedule never exceeds capacity. Rounds extend if the backlog
        outlives the schedule."""
        if self.apply_rate_per_s <= 0.0:
            return schedule
        from collections import deque

        from corrosion_tpu.sim.engine import Schedule

        cap = self.apply_rate_per_s * self.round_ms / 1000.0
        writes = np.asarray(schedule.writes)
        rounds, n_writers = writes.shape
        if writes.sum(axis=1).max() <= cap:
            return schedule  # never over capacity: bit-identical schedule
        queue: deque = deque()
        out_rows = []
        credit = 0.0
        r = 0
        while r < rounds or queue:
            if r < rounds:
                remaining = writes[r].astype(np.int64).copy()
                while remaining.sum() > 0:  # round-robin across writers
                    for w in range(n_writers):
                        if remaining[w] > 0:
                            queue.append(w)
                            remaining[w] -= 1
            credit += cap
            admit = int(credit)
            credit -= admit
            row = np.zeros(n_writers, writes.dtype)
            while admit > 0 and queue:
                row[queue.popleft()] += 1
                admit -= 1
            out_rows.append(row)
            r += 1
        if len(out_rows) != rounds and any(
            ax is not None for ax in (
                schedule.kill, schedule.revive, schedule.partition,
                schedule.loss, schedule.probe_loss, schedule.wipe,
            )
        ):
            raise ValueError(
                "defer_schedule extended the round count but per-round "
                "fault axes are already attached — defer BEFORE applying "
                "plans/models (kernel_replay's order)"
            )
        return Schedule(
            writes=np.stack(out_rows),
            kill=schedule.kill,
            revive=schedule.revive,
            partition=schedule.partition,
            sample_writer=schedule.sample_writer,
            sample_ver=schedule.sample_ver,
            sample_round=schedule.sample_round,
            loss=schedule.loss,
            probe_loss=schedule.probe_loss,
            wipe=schedule.wipe,
        )

    # -- serialization -------------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "schema": MODEL_SCHEMA,
            "round_ms": self.round_ms,
            "flush_ms": self.flush_ms,
            "regions": self.regions,
            "pair_rtt_p50_ms": self.pair_rtt_p50_ms,
            "pair_rtt_p99_ms": self.pair_rtt_p99_ms,
            "ring_occupancy": self.ring_occupancy,
            "pair_miss": self.pair_miss,
            "probe_loss": self.probe_loss,
            "apply_ms": self.apply_ms,
            "vis_offset_rounds": self.vis_offset_rounds,
            "apply_rate_per_s": self.apply_rate_per_s,
            "provenance": self.provenance,
        }

    def to_json(self, indent: int | None = None) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @classmethod
    def from_dict(cls, d: dict) -> "RoundModel":
        if d.get("schema") != MODEL_SCHEMA:
            raise ValueError(f"not a {MODEL_SCHEMA} model: {d.get('schema')}")
        if not d.get("provenance"):
            raise ValueError(
                "round model has no provenance block — a calibration "
                "whose inputs are unstated cannot back a wall-clock claim"
            )
        return cls(
            round_ms=float(d["round_ms"]),
            flush_ms=float(d["flush_ms"]),
            regions=int(d["regions"]),
            pair_rtt_p50_ms=d["pair_rtt_p50_ms"],
            pair_rtt_p99_ms=d["pair_rtt_p99_ms"],
            ring_occupancy=d["ring_occupancy"],
            pair_miss=d["pair_miss"],
            probe_loss=float(d.get("probe_loss", 0.0)),
            apply_ms=float(d.get("apply_ms", 0.0)),
            vis_offset_rounds=float(d.get("vis_offset_rounds", 0.5)),
            apply_rate_per_s=float(d.get("apply_rate_per_s", 0.0)),
            provenance=dict(d["provenance"]),
        )

    @classmethod
    def from_json(cls, s: str) -> "RoundModel":
        return cls.from_dict(json.loads(s))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json(indent=2) + "\n")

    @classmethod
    def load(cls, path: str) -> "RoundModel":
        with open(path) as f:
            return cls.from_json(f.read())

    def describe(self) -> str:
        lb = self.loss_by_region()
        return (
            f"round_ms={self.round_ms:.2f} (flush {self.flush_ms:g} ms) "
            f"regions={self.regions} "
            f"miss(max region)={float(lb.max()):.4f} "
            f"probe_loss={self.probe_loss:.4f} "
            f"apply_rate={self.apply_rate_per_s:.0f}/s"
            + (" [identity]" if self.is_identity else "")
        )


# ---------------------------------------------------------------------------
# Derivations.


def _miss_from_one_way(one_way_ms: np.ndarray, round_ms: float) -> float:
    """P(delivery slips past the round boundary) for measured one-way
    transit samples: a write commits uniformly within the round, so a
    message with transit ``d`` misses the closing flush with probability
    ``min(d / round_ms, 1)``; average over the samples."""
    d = np.asarray(one_way_ms, np.float64)
    return float(np.minimum(d / round_ms, 1.0).mean()) if d.size else 0.0


def derive_model(
    rtt_samples_by_pair: dict,
    flush_ms: float,
    apply_ms: float = 0.0,
    apply_rate_per_s: float = 0.0,
    regions: int = 1,
    probe_attempts: int = 0,
    probe_timeouts: int = 0,
    provenance: dict | None = None,
) -> RoundModel:
    """Build a :class:`RoundModel` from raw probe-RTT samples.

    ``rtt_samples_by_pair`` maps ``(receiver_region, source_region)`` to a
    list of measured RTTs in ms; a missing pair reuses the worst measured
    pair (conservative). ``round_ms`` is derived as the delivery-pipeline
    tick — flush tick + receiver apply/ingest tick + cluster-wide one-way
    p50 (messages must transit AND be applied before they are visible) —
    then ``pair_miss`` is evaluated against that calibrated round length.
    """
    if not rtt_samples_by_pair:
        raise ValueError("need at least one measured region pair")
    if not flush_ms > 0.0:
        raise ValueError(f"flush_ms must be positive: {flush_ms}")
    all_rtts = np.concatenate([
        np.asarray(v, np.float64) for v in rtt_samples_by_pair.values()
    ])
    if all_rtts.size == 0:
        raise ValueError("every measured pair is empty")
    one_way_p50 = _percentile(all_rtts, 50) / 2.0
    round_ms = flush_ms + apply_ms + one_way_p50

    worst_pair = max(
        rtt_samples_by_pair,
        key=lambda k: _percentile(rtt_samples_by_pair[k], 50)
        if len(rtt_samples_by_pair[k]) else -1.0,
    )
    p50 = [[0.0] * regions for _ in range(regions)]
    p99 = [[0.0] * regions for _ in range(regions)]
    occ = [
        [[0] * len(RING_REPR_MS) for _ in range(regions)]
        for _ in range(regions)
    ]
    miss = [[0.0] * regions for _ in range(regions)]
    for i in range(regions):
        for j in range(regions):
            xs = rtt_samples_by_pair.get(
                (i, j), rtt_samples_by_pair[worst_pair]
            )
            xs = np.asarray(xs, np.float64)
            if xs.size == 0:
                xs = np.asarray(rtt_samples_by_pair[worst_pair], np.float64)
            p50[i][j] = round(_percentile(xs, 50), 4)
            p99[i][j] = round(_percentile(xs, 99), 4)
            for x in xs:
                occ[i][j][rtt_ring(float(x))] += 1
            miss[i][j] = round(_miss_from_one_way(xs / 2.0, round_ms), 6)
    probe_loss = (
        probe_timeouts / probe_attempts if probe_attempts > 0 else 0.0
    )
    return RoundModel(
        round_ms=round(round_ms, 4),
        flush_ms=float(flush_ms),
        regions=regions,
        pair_rtt_p50_ms=p50,
        pair_rtt_p99_ms=p99,
        ring_occupancy=occ,
        pair_miss=miss,
        probe_loss=round(probe_loss, 6),
        apply_ms=float(apply_ms),
        apply_rate_per_s=round(float(apply_rate_per_s), 2),
        provenance=dict(provenance or {}),
    )


def from_ring_occupancy(
    occupancy,
    flush_ms: float,
    apply_ms: float = 0.0,
    probe_loss: float = 0.0,
    provenance: dict | None = None,
) -> RoundModel:
    """Build a model from RTT **ring occupancy** alone — sample counts
    per the reference's ring buckets, [R][R][rings]. This is how host
    ``Members`` state (``MemberState.rtts`` bucketed by ``rtt_ring``) or
    a kernel topology's ring-class matrix (``Topology.region_rtt``,
    one-hot occupancy) becomes a calibration input: each bucket is
    represented by ``RING_REPR_MS``."""
    occ = np.asarray(occupancy, np.float64)
    if occ.ndim != 3 or occ.shape[0] != occ.shape[1] or (
        occ.shape[2] != len(RING_REPR_MS)
    ):
        raise ValueError(
            f"occupancy must be [R][R][{len(RING_REPR_MS)}], got {occ.shape}"
        )
    if occ.sum(axis=2).min() <= 0:
        raise ValueError("every region pair needs >= 1 ring sample")
    regions = occ.shape[0]
    repr_ms = np.asarray(RING_REPR_MS, np.float64)
    w = occ / occ.sum(axis=2, keepdims=True)  # [R][R][rings] weights
    pair_mean = (w * repr_ms).sum(axis=2)  # [R][R] representative RTT
    one_way_p50 = float(np.median(pair_mean)) / 2.0
    round_ms = flush_ms + apply_ms + one_way_p50
    miss = (w * np.minimum((repr_ms / 2.0) / round_ms, 1.0)).sum(axis=2)
    # Bucket-resolution percentiles: the edge of the bucket where the
    # weighted CDF crosses the quantile.
    cdf = np.cumsum(w, axis=2)

    def q_edge(q: float) -> np.ndarray:
        idx = (cdf < q).sum(axis=2)
        idx = np.minimum(idx, len(repr_ms) - 1)
        return repr_ms[idx]

    return RoundModel(
        round_ms=round(round_ms, 4),
        flush_ms=float(flush_ms),
        regions=regions,
        pair_rtt_p50_ms=np.round(q_edge(0.5), 4).tolist(),
        pair_rtt_p99_ms=np.round(q_edge(0.99), 4).tolist(),
        ring_occupancy=occ.astype(np.int64).tolist(),
        pair_miss=np.round(miss, 6).tolist(),
        probe_loss=float(probe_loss),
        apply_ms=float(apply_ms),
        provenance=dict(provenance or {"source": "ring-occupancy"}),
    )


def from_characterization(
    char: dict,
    flush_ms: float,
    apply_ms: float = 0.0,
    provenance: dict | None = None,
) -> RoundModel:
    """Build a single-region model from a
    ``scripts/transport_characterization.py`` artifact (the under-bulk
    probe percentiles and probe-loss tail — the worst case the probe
    plane measured). The two percentiles stand in for the distribution
    as a two-point approximation: 3/4 of the mass at p50, 1/4 at p99
    (documented in docs/FIDELITY.md)."""
    under = char.get("probe_rtt_under_bulk_ms") or {}
    p50, p99 = under.get("p50"), under.get("p99")
    if p50 is None or p99 is None:
        raise ValueError(
            "characterization artifact lacks probe_rtt_under_bulk_ms "
            "p50/p99 — cannot calibrate from it"
        )
    samples = {(0, 0): [float(p50)] * 3 + [float(p99)]}
    model = derive_model(
        samples, flush_ms=flush_ms, apply_ms=apply_ms, regions=1,
        provenance=provenance or {
            "source": "transport-characterization",
            "rows": char.get("rows"),
        },
    )
    # dataclasses.replace re-runs __post_init__, so an out-of-range loss
    # in a corrupted artifact is rejected HERE, not at a later load of
    # the saved model.
    from dataclasses import replace as _replace

    return _replace(
        model,
        probe_loss=float(char.get("probe_loss_under_bulk", 0.0) or 0.0),
    )


def identity_model(regions: int = 1) -> RoundModel:
    """The uncalibrated identification as a model: the reference 500 ms
    round, zero miss, zero probe loss — compiles to NO fault axes, so
    replays under it are bit-identical to pre-fidelity replays."""
    z = [[0.0] * regions for _ in range(regions)]
    occ = [
        [[1] + [0] * (len(RING_REPR_MS) - 1) for _ in range(regions)]
        for _ in range(regions)
    ]
    return RoundModel(
        round_ms=REFERENCE_ROUND_MS,
        flush_ms=REFERENCE_ROUND_MS,
        regions=regions,
        pair_rtt_p50_ms=z,
        pair_rtt_p99_ms=[row[:] for row in z],
        ring_occupancy=occ,
        pair_miss=[row[:] for row in z],
        probe_loss=0.0,
        provenance={"source": "identity"},
    )


# ---------------------------------------------------------------------------
# Live measurement: active probe sampling through the real SWIM plane.


async def _measure_apply_rate(agents, train: int = 12) -> float:
    """Measured receiver apply drain rate: a back-to-back calibration
    write train into ``tests2`` (DISJOINT from every compared workload's
    ``tests`` table) on agent 0, its deliveries timestamped on a remote
    agent's subscription. The drain rate — (train-1) / spread of the
    remote arrival times — is the under-load signal the burst scenario's
    apply-backlog term needs. Returns 0.0 (unmeasured) for a 1-agent
    cluster or a degenerate spread."""
    import asyncio
    import time

    if len(agents) < 2 or train < 2:
        return 0.0
    stream = await agents[1].client.subscribe("SELECT id, text FROM tests2")
    arrivals: list[float] = []

    async def consume() -> None:
        async for ev in stream:
            if "change" in ev:
                arrivals.append(time.perf_counter())
                if len(arrivals) >= train:
                    return

    task = asyncio.ensure_future(consume())
    try:
        await asyncio.sleep(0.05)  # let the empty snapshot drain
        # One transaction per row: the train must be `train` COMMITS
        # (each its own version + broadcast frame), not one batched one.
        for i in range(train):
            await agents[0].client.execute([
                ["INSERT INTO tests2 (id, text) VALUES (?, 'cal')", [i]]
            ])
        await asyncio.wait_for(task, 20.0)
    except (asyncio.TimeoutError, ConnectionError, OSError):
        task.cancel()
        return 0.0
    finally:
        stream.close()
    if len(arrivals) < 2:
        return 0.0
    spread_s = arrivals[-1] - arrivals[0]
    return (len(arrivals) - 1) / spread_s if spread_s > 0 else 0.0


async def measure_live(agents, probes: int = 40, gap_s: float = 0.01) -> dict:
    """Sample probe RTTs between every ordered pair of live test agents
    through the real SWIM UDP plane (``swim._probe``, the same path
    ``scripts/transport_characterization.py`` measures), measure the
    receiver apply drain rate on a disjoint write train, and read the
    configured flush tick. Returns the raw measurement dict
    :func:`calibrate_live` derives a model from."""
    import asyncio
    import time

    samples: dict = {}
    attempts = timeouts = 0
    for a in agents:
        for b in agents:
            if a is b:
                continue
            key = (0, 0)  # loopback cluster: one region
            rtts = samples.setdefault(key, [])
            for _ in range(probes):
                t0 = time.perf_counter()
                ok = await a.agent.swim._probe(b.agent.gossip_addr)
                attempts += 1
                if ok:
                    rtts.append((time.perf_counter() - t0) * 1000.0)
                else:
                    timeouts += 1
                await asyncio.sleep(gap_s)
    # Fold in any passively accumulated host membership RTT state too —
    # the rtt_ring buckets the probe loop has been feeding.
    member_samples = [
        float(r)
        for a in agents
        for m in a.agent.members.states.values()
        for r in m.rtts
    ]
    if member_samples:
        samples.setdefault((0, 0), []).extend(member_samples)
    apply_rate = await _measure_apply_rate(agents)
    return {
        "rtt_samples_by_pair": samples,
        "flush_ms": agents[0].agent.cfg.broadcast_interval * 1000.0,
        # Receiver-side apply batching: handle_changes ingest linger —
        # part of the delivery pipeline a kernel round aggregates.
        "apply_ms": agents[0].agent.cfg.ingest_linger * 1000.0,
        "apply_rate_per_s": apply_rate,
        "probe_attempts": attempts,
        "probe_timeouts": timeouts,
        "nodes": len(agents),
    }


async def calibrate_live(
    agents, probes: int = 40, provenance: dict | None = None
) -> RoundModel:
    """Measure a live cluster and derive its :class:`RoundModel`."""
    m = await measure_live(agents, probes=probes)
    prov = {
        "source": "live",
        "nodes": m["nodes"],
        "probe_attempts": m["probe_attempts"],
        "probe_timeouts": m["probe_timeouts"],
        **(provenance or {}),
    }
    return derive_model(
        m["rtt_samples_by_pair"],
        flush_ms=m["flush_ms"],
        apply_ms=m["apply_ms"],
        apply_rate_per_s=m["apply_rate_per_s"],
        regions=1,
        probe_attempts=m["probe_attempts"],
        probe_timeouts=m["probe_timeouts"],
        provenance=prov,
    )
