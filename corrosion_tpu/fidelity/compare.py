"""Mixed-mode live-vs-kernel comparison: the divergence measurement.

One entry point (:func:`compare_live_kernel`) runs the SAME write
workload through both sides of the dispatch seam:

- **live**: an in-process multi-agent cluster (``agent/testing``, real
  TCP/UDP over loopback) with a chained :class:`sim.trace.Trace`
  recorder on every agent and a per-node subscription watcher sampling
  per-write first-visibility wall timestamps from the NDJSON
  subscription plane;
- **kernel**: the recorded trace replayed through the simulator twice —
  once **calibrated** (bucketed at the :class:`RoundModel`'s measured
  ``round_ms`` with the model's miss/probe-loss axes compiled in through
  the chaos plane) and once **uncalibrated** (the hardcoded 500 ms
  reference identification, no axes).

Both sides' visibility latencies land in the existing
``delivery_latency_hist`` bucket space (``telemetry.VIS_LAT_EDGES``,
bucketed by ``health.latency_bucket``) **in calibrated-round units**, so
the histograms are directly comparable: live wall-ms divide by the
calibrated round length; kernel round-latencies rescale by
``round_ms_used / round_ms_calibrated``. The divergence verdict per
kernel run is the bucket-space earth-mover's distance (sum of |ΔCDF|
over buckets) against the live CDF — with the bucket-resolution
Kolmogorov distance and the full per-bucket diff reported alongside —
plus p50/p99 bucket deltas, per-percentile latency ratios, and the
time-to-convergence delta. The acceptance claim
``scripts/fidelity_smoke.py`` gates: the calibrated replay's CDF lands
strictly closer to the live cluster's than the uncalibrated replay's.
"""

from __future__ import annotations

import asyncio
import time

import numpy as np

from corrosion_tpu.fidelity.calibrate import (
    REFERENCE_ROUND_MS,
    RoundModel,
    calibrate_live,
    trace_fingerprint,
)

# Row-id namespace: writer w's k-th row is w * WRITER_STRIDE + k, so a
# delivered change maps back to its (writer, seq) without a lookup table.
WRITER_STRIDE = 1_000_000


def _n_buckets() -> int:
    from corrosion_tpu.sim.telemetry import VIS_LAT_EDGES

    return len(VIS_LAT_EDGES) + 1


def bucket_hist(lat_cal_rounds) -> list:
    """Histogram counts over the fixed delivery-latency buckets for
    latencies expressed in calibrated rounds (``health.latency_bucket``
    is the one bucketize both sides share)."""
    from corrosion_tpu.sim.health import latency_bucket

    counts = [0] * _n_buckets()
    for x in np.asarray(lat_cal_rounds, np.float64).ravel():
        counts[latency_bucket(float(x))] += 1
    return counts


def hist_cdf(counts) -> list:
    counts = np.asarray(counts, np.float64)
    total = counts.sum()
    return (np.cumsum(counts) / total).tolist() if total > 0 else []


def divergence_verdict(live_hist, kernel_hist) -> dict:
    """Bucket-space divergence of a kernel histogram from the live one.

    The headline metric is ``cdf_distance`` — the sum of per-bucket
    |ΔCDF|, which for a 1-D histogram IS the earth-mover's distance in
    bucket units ("on average, how many buckets is the kernel's
    latency mass displaced from the live cluster's"). The max
    (Kolmogorov distance at bucket resolution) and the full per-bucket
    diff vector are reported alongside; p50/p99 bucket deltas reuse
    ``health.cdf_quantile``. EMD is the gated ordering metric because it
    is robust to single-bucket edge jitter: a replay that is 3 buckets
    off for most of its mass can never out-score one within 1 bucket by
    landing a lucky bucket boundary.
    """
    from corrosion_tpu.sim.health import cdf_quantile

    lc, kc = hist_cdf(live_hist), hist_cdf(kernel_hist)
    if not lc or not kc:
        raise ValueError("divergence needs non-empty live AND kernel hists")
    per_bucket = [round(abs(a - b), 6) for a, b in zip(lc, kc)]
    lp50, _ = cdf_quantile(np.asarray(live_hist, np.float64), 0.50)
    lp99, _ = cdf_quantile(np.asarray(live_hist, np.float64), 0.99)
    kp50, _ = cdf_quantile(np.asarray(kernel_hist, np.float64), 0.50)
    kp99, _ = cdf_quantile(np.asarray(kernel_hist, np.float64), 0.99)
    return {
        "cdf_distance": round(sum(per_bucket), 6),  # EMD, bucket units
        "kolmogorov": max(per_bucket),
        "per_bucket_cdf_diff": per_bucket,
        "p50_bucket": kp50,
        "p99_bucket": kp99,
        "p50_bucket_delta": abs(kp50 - lp50),
        "p99_bucket_delta": abs(kp99 - lp99),
    }


# ---------------------------------------------------------------------------
# Live side.


class VisibilityWatcher:
    """One agent's subscription stream, recording the wall time (ms,
    ``time.time`` basis — the same basis as the trace's HLC physical
    timestamps) each row id FIRST became visible on this node."""

    def __init__(self, node: int, client, sql: str):
        self.node = node
        self.sql = sql
        self.client = client
        self.seen_ms: dict[int, float] = {}
        self.stream = None
        self._task: asyncio.Task | None = None

    async def start(self) -> None:
        self.stream = await self.client.subscribe(self.sql)
        self._task = asyncio.ensure_future(self._run())

    async def _run(self) -> None:
        try:
            async for ev in self.stream:
                now_ms = time.time() * 1000.0
                if "change" in ev:
                    _kind, _rowid, cells, _cid = ev["change"]
                    self.seen_ms.setdefault(int(cells[0]), now_ms)
                elif "row" in ev:
                    _rowid, cells = ev["row"]
                    self.seen_ms.setdefault(int(cells[0]), now_ms)
        except (ConnectionError, OSError, asyncio.IncompleteReadError,
                ValueError):
            pass

    async def stop(self) -> None:
        if self.stream is not None:
            self.stream.close()
        task, self._task = self._task, None
        if task is not None:
            try:
                await asyncio.wait_for(task, 5.0)
            except asyncio.TimeoutError:
                pass  # wait_for already cancelled the watcher task
            except asyncio.CancelledError:
                task.cancel()
                raise  # we were cancelled: propagate, don't absorb


async def run_live_workload(
    data_dir: str,
    arrivals,
    n_agents: int = 3,
    settle_timeout_s: float = 30.0,
    probes: int = 40,
    model: RoundModel | None = None,
    progress=None,
) -> dict:
    """Run a write workload against a live loopback cluster, tracing
    commits and sampling per-write visibility.

    ``arrivals`` is a list of ``(t_s, writer_idx)`` — writer ``w``'s
    writes fire open-loop at their scheduled offsets and commit rows
    ``w * WRITER_STRIDE + seq``. Returns the merged trace, per-(node,
    write) visibility latencies in wall ms, the calibrated
    :class:`RoundModel` measured on the same cluster (skipped when a
    pre-built ``model`` is supplied — no probe sampling or apply-rate
    train runs), and run facts.
    """
    from corrosion_tpu.agent.testing import (
        launch_test_cluster, poll_until, stop_cluster,
    )
    from corrosion_tpu.sim.trace import Trace

    def note(msg):
        if progress is not None:
            progress.write(f"[fidelity] {msg}\n")
            progress.flush()

    writers = sorted({w for _t, w in arrivals})
    if writers and writers[-1] >= n_agents:
        raise ValueError(
            f"workload writer {writers[-1]} needs >= {writers[-1] + 1} "
            f"agents, have {n_agents}"
        )
    agents = []
    watchers: list[VisibilityWatcher] = []
    trace = Trace()
    try:
        agents = await launch_test_cluster(data_dir, n_agents)
        note(f"{n_agents} agents up with full membership")
        for i, a in enumerate(agents):
            w = VisibilityWatcher(i, a.client, "SELECT id, text FROM tests")
            await w.start()
            watchers.append(w)

        # Calibrate on the SAME cluster the workload runs on — BEFORE
        # attaching the trace recorders, so the calibration write train
        # (tests2) never pollutes the compared workload's trace. A
        # pre-built model skips the measurement entirely.
        if model is None:
            model = await calibrate_live(agents, probes=probes)
            note(f"calibrated: {model.describe()}")
        else:
            note(f"pre-built model: {model.describe()}")
        for a in agents:
            trace.record(a.agent)

        # Open-loop write storm: arrivals fire on the wall-clock grid;
        # per-writer sequences stay ordered (versions must be contiguous
        # per actor for schedule_from_trace).
        seqs = {w: 0 for w in writers}
        loop = asyncio.get_running_loop()
        t0 = loop.time()

        async def fire(w: int, seq: int, at_s: float) -> None:
            delay = t0 + at_s - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            row = w * WRITER_STRIDE + seq
            await agents[w].client.execute([[
                "INSERT INTO tests (id, text) VALUES (?, ?)",
                [row, f"fid-w{w}-{seq}"],
            ]])

        # One ordered lane per writer; lanes run concurrently.
        lanes: dict[int, list] = {w: [] for w in writers}
        for t_s, w in sorted(arrivals):
            lanes[w].append((t_s, seqs[w]))
            seqs[w] += 1

        async def lane(w: int) -> None:
            for t_s, seq in lanes[w]:
                await fire(w, seq, t_s)

        note(f"firing {len(arrivals)} writes over {len(writers)} writers")
        await asyncio.gather(*(lane(w) for w in writers))

        all_rows = {
            w * WRITER_STRIDE + s for w in writers for s in range(seqs[w])
        }

        async def all_seen():
            return all(
                all_rows <= set(wt.seen_ms) for wt in watchers
            )

        try:
            await poll_until(all_seen, timeout=settle_timeout_s)
            note("all writes visible on every node")
        except TimeoutError:
            # Partial visibility is a RESULT (reported as unseen pairs),
            # not a harness crash — the divergence report must still
            # emit so the standing lane can flag it.
            note("settle timeout: some writes never became visible")
    finally:
        for w in watchers:
            await w.stop()
        actor_ids = [a.agent.actor_id for a in agents]
        await stop_cluster(agents)

    # Commit wall-ms per row id from the trace (actor w's k-th version is
    # its k-th fired row — per-writer lanes are strictly sequential).
    commit_ms: dict[int, float] = {}
    per_actor_count: dict[str, int] = {}
    for t_ms, actor, _v in sorted(trace.events):
        w = actor_ids.index(actor)
        k = per_actor_count.get(actor, 0)
        per_actor_count[actor] = k + 1
        commit_ms[w * WRITER_STRIDE + k] = float(t_ms)

    # REMOTE pairs only — the kernel side applies the same filter
    # (see kernel_replay): visibility of a write on nodes OTHER than
    # its writer is the dissemination quantity being validated.
    lat_ms: list[float] = []
    unseen = 0
    for wt in watchers:
        for row, t_commit in commit_ms.items():
            if row // WRITER_STRIDE == wt.node:
                continue  # the writer's own node
            t_seen = wt.seen_ms.get(row)
            if t_seen is None:
                unseen += 1
            else:
                lat_ms.append(max(t_seen - t_commit, 0.0))
    ttc_ms = (
        max(
            t for wt in watchers
            for r, t in wt.seen_ms.items()
            if r in commit_ms and r // WRITER_STRIDE != wt.node
        ) - min(commit_ms.values())
        if lat_ms else None
    )
    return {
        "trace": trace,
        "model": model,
        "lat_ms": lat_ms,
        "unseen": unseen,
        "pairs": len(lat_ms) + unseen,
        "ttc_ms": ttc_ms,
        "nodes": len(watchers),
        "writes": len(commit_ms),
    }


# ---------------------------------------------------------------------------
# Kernel side.


def kernel_replay(
    trace,
    round_ms: float,
    n_nodes: int,
    model: RoundModel | None = None,
    drain_rounds: int = 60,
    seed: int = 0,
    vis_offset_rounds: float = 0.5,
    **gossip_kw,
) -> dict:
    """Replay a recorded trace in the kernel at ``round_ms``, optionally
    with a model's compiled fault axes merged in (``RoundModel.apply`` →
    the chaos plane's ``apply_plan``). Returns per-pair visibility
    latencies in ROUNDS plus convergence facts. ``vis_offset_rounds`` is
    the round→wall discretization correction (RoundModel docstring) the
    wall-clock projections add — applied identically to calibrated and
    uncalibrated replays."""
    from corrosion_tpu.models.baselines import _cfg
    from corrosion_tpu.sim.engine import simulate
    from corrosion_tpu.sim.trace import schedule_from_trace

    actors, sched = schedule_from_trace(
        trace, round_ms=round_ms, drain_rounds=drain_rounds
    )
    w = len(actors)
    if n_nodes < w:
        raise ValueError(f"n_nodes {n_nodes} < {w} recorded writers")
    if model is not None:
        # Capacity deferral FIRST (it may extend the round count), then
        # the compiled miss/probe-loss axes.
        sched = model.defer_schedule(sched)
        sched = model.apply(sched, n_nodes=n_nodes)
    max_writes = int(sched.writes.max())
    cfg, topo = _cfg(
        n_nodes,
        writers=list(range(w)),
        sync_interval=4,
        n_cells=0,
        max_writes_per_round=max(4, max_writes),
        **gossip_kw,
    )
    final, curves = simulate(cfg, topo, sched, seed=seed)
    vis = np.asarray(final.vis_round)  # [S, N]
    lat_rounds = (
        vis.astype(np.float64) - sched.sample_round[:, None].astype(np.float64)
    )
    # REMOTE pairs only: a writer's visibility of its own write is a
    # local-matcher fact on both sides (live: the sub matcher fires on
    # the write path, ~instant; kernel: commit-round visibility), not a
    # dissemination measurement — it would only pad bucket 0 and, under
    # capacity deferral, pad it inconsistently.
    remote = np.ones_like(vis, dtype=bool)
    remote[np.arange(len(sched.sample_writer)), sched.sample_writer] = False
    seen = (vis >= 0) & remote
    unseen = int(((vis < 0) & remote).sum())
    ttc_ms = (
        float(
            (vis[remote].max() + vis_offset_rounds
             - sched.sample_round.min()) * round_ms
        )
        if unseen == 0 and vis.size and remote.any() else None
    )
    return {
        "round_ms": round_ms,
        "rounds": sched.rounds,
        "lat_rounds": lat_rounds[seen].ravel(),
        "vis_offset_rounds": vis_offset_rounds,
        "unseen": unseen,
        "pairs": int(remote.sum()),
        "ttc_ms": ttc_ms,
        "need_last": float(np.asarray(curves["need"])[-1]),
    }


# ---------------------------------------------------------------------------
# The whole comparison.


def _side_report(live: dict, rep: dict, cal_round_ms: float) -> dict:
    """Fold one kernel replay into the common calibrated bucket space and
    attach its divergence verdict against the live histograms."""
    from corrosion_tpu.sim.telemetry import VIS_LAT_EDGES

    scale = rep["round_ms"] / cal_round_ms
    offset = rep["vis_offset_rounds"]
    hist = bucket_hist((np.asarray(rep["lat_rounds"]) + offset) * scale)
    live_hist = bucket_hist(np.asarray(live["lat_ms"]) / cal_round_ms)
    if sum(live_hist) == 0 or sum(hist) == 0:
        # Nothing ever delivered on one side: still a REPORT (the gate's
        # unseen/missing-ceiling breaches flag it), never a crash — the
        # standing lane must emit its artifact for a broken run too.
        return {
            "round_ms": round(rep["round_ms"], 4),
            "rounds": rep["rounds"],
            "pairs": rep["pairs"],
            "unseen": rep["unseen"],
            "hist": hist,
            "cdf": [],
            "ttc_ms": rep["ttc_ms"],
            "ttc_delta_ms": None,
        }
    v = divergence_verdict(live_hist, hist)
    edges_ms = [e * cal_round_ms for e in VIS_LAT_EDGES]

    def edge_ms(bucket: int) -> float:
        return (
            edges_ms[bucket] if bucket < len(edges_ms) else float("inf")
        )

    live_p50 = np.percentile(live["lat_ms"], 50) if live["lat_ms"] else None
    live_p99 = np.percentile(live["lat_ms"], 99) if live["lat_ms"] else None
    kern = (np.asarray(rep["lat_rounds"]) + offset) * rep["round_ms"]
    out = {
        "round_ms": round(rep["round_ms"], 4),
        "rounds": rep["rounds"],
        "pairs": rep["pairs"],
        "unseen": rep["unseen"],
        "hist": hist,
        "cdf": [round(c, 6) for c in hist_cdf(hist)],
        **v,
        "p50_edge_ms": edge_ms(v["p50_bucket"]),
        "p99_edge_ms": edge_ms(v["p99_bucket"]),
        "ttc_ms": rep["ttc_ms"],
        "ttc_delta_ms": (
            None if rep["ttc_ms"] is None or live["ttc_ms"] is None
            else round(abs(rep["ttc_ms"] - live["ttc_ms"]), 2)
        ),
    }
    # Each ratio guards on its OWN denominator: a loopback live p50 can
    # legitimately clamp to 0.0 ms while p99 stays well-defined.
    if kern.size and live_p50 is not None and live_p50 > 0:
        out["p50_ratio"] = round(float(np.percentile(kern, 50)) / live_p50, 3)
    if kern.size and live_p99 is not None and live_p99 > 0:
        out["p99_ratio"] = round(float(np.percentile(kern, 99)) / live_p99, 3)
    return out


async def compare_live_kernel(
    data_dir: str,
    arrivals,
    n_agents: int = 3,
    model: RoundModel | None = None,
    seed: int = 0,
    settle_timeout_s: float = 30.0,
    progress=None,
) -> dict:
    """The mixed-mode harness: one workload, both sides, calibrated and
    uncalibrated kernel replays, one divergence report block. A
    pre-built ``model`` skips the in-run calibration (CLI ``--model``)."""
    live = await run_live_workload(
        data_dir, arrivals, n_agents=n_agents,
        settle_timeout_s=settle_timeout_s, model=model, progress=progress,
    )
    mdl = live["model"]
    cal = kernel_replay(
        live["trace"], mdl.round_ms, n_nodes=live["nodes"], model=mdl,
        seed=seed, vis_offset_rounds=mdl.vis_offset_rounds,
    )
    uncal = kernel_replay(
        live["trace"], REFERENCE_ROUND_MS, n_nodes=live["nodes"], model=None,
        seed=seed, vis_offset_rounds=mdl.vis_offset_rounds,
    )
    live_hist = bucket_hist(np.asarray(live["lat_ms"]) / mdl.round_ms)
    cal_rep = _side_report(live, cal, mdl.round_ms)
    uncal_rep = _side_report(live, uncal, mdl.round_ms)
    return {
        "trace_fingerprint": trace_fingerprint(live["trace"].events),
        "model": mdl.to_dict(),
        "live": {
            "nodes": live["nodes"],
            "writes": live["writes"],
            "pairs": live["pairs"],
            "unseen": live["unseen"],
            "hist": live_hist,
            "cdf": [round(c, 6) for c in hist_cdf(live_hist)],
            "lat_p50_ms": (
                round(float(np.percentile(live["lat_ms"], 50)), 2)
                if live["lat_ms"] else None
            ),
            "lat_p99_ms": (
                round(float(np.percentile(live["lat_ms"], 99)), 2)
                if live["lat_ms"] else None
            ),
            "ttc_ms": (
                round(live["ttc_ms"], 2) if live["ttc_ms"] is not None
                else None
            ),
        },
        "calibrated": cal_rep,
        "uncalibrated": uncal_rep,
        # Strictly-closer ordering; a degraded side (no CDF — nothing
        # delivered) can never claim the win.
        "calibrated_closer": (
            cal_rep.get("cdf_distance") is not None
            and uncal_rep.get("cdf_distance") is not None
            and cal_rep["cdf_distance"] < uncal_rep["cdf_distance"]
        ),
    }
