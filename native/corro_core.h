/* Shared C core for the corrosion-tpu native runtime components.
 *
 * Implements the byte-level primitives both native artifacts build on:
 *
 *   - varint + zigzag codec (the PK / wire integer encoding of
 *     corrosion_tpu/core/values.py, itself mirroring the packed-column
 *     format of the reference's pubsub.rs:2115-2283)
 *   - packed-column (PK blob) encode/validate/iterate
 *   - exact SQLite cross-type value comparison (NULL < numeric < text <
 *     blob, ints and reals compared exactly) — the LWW "biggest value
 *     wins" tie-break of the reference's cr-sqlite engine
 *     (doc/crdts.md:15-16)
 *
 * Used by:
 *   - corro_native.c  (CPython extension module corrosion_tpu._corro_native)
 *   - crdt_ext.c      (SQLite run-time loadable extension, the cr-sqlite
 *                      analogue loaded into every Store connection)
 */
#ifndef CORRO_CORE_H
#define CORRO_CORE_H

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

/* Column type tags — ordered like SQLite's cross-type ordering so tag
 * comparison gives type precedence (values.py T_NULL..T_BLOB). */
enum {
  CORRO_T_NULL = 0,
  CORRO_T_INT = 1,
  CORRO_T_REAL = 2,
  CORRO_T_TEXT = 3,
  CORRO_T_BLOB = 4,
};

/* ---- growable byte buffer ---------------------------------------------- */

typedef struct {
  uint8_t *data;
  size_t len;
  size_t cap;
  int oom;
} corro_buf;

static inline void corro_buf_init(corro_buf *b) {
  b->data = NULL;
  b->len = 0;
  b->cap = 0;
  b->oom = 0;
}

static inline void corro_buf_free(corro_buf *b) {
  free(b->data);
  corro_buf_init(b);
}

static inline int corro_buf_reserve(corro_buf *b, size_t extra) {
  if (b->oom) return -1;
  if (b->len + extra <= b->cap) return 0;
  size_t cap = b->cap ? b->cap : 64;
  while (cap < b->len + extra) cap *= 2;
  uint8_t *p = (uint8_t *)realloc(b->data, cap);
  if (!p) {
    b->oom = 1;
    return -1;
  }
  b->data = p;
  b->cap = cap;
  return 0;
}

static inline void corro_buf_put(corro_buf *b, const void *src, size_t n) {
  if (corro_buf_reserve(b, n)) return;
  memcpy(b->data + b->len, src, n);
  b->len += n;
}

static inline void corro_buf_put_u8(corro_buf *b, uint8_t v) {
  corro_buf_put(b, &v, 1);
}

/* ---- varint + zigzag ---------------------------------------------------- */

static inline void corro_write_varint(corro_buf *b, uint64_t n) {
  while (1) {
    uint8_t byte = (uint8_t)(n & 0x7F);
    n >>= 7;
    if (n) {
      corro_buf_put_u8(b, byte | 0x80);
    } else {
      corro_buf_put_u8(b, byte);
      return;
    }
  }
}

/* Returns bytes consumed, or 0 on truncation/overflow. */
static inline size_t corro_read_varint(const uint8_t *buf, size_t len,
                                       uint64_t *out) {
  uint64_t n = 0;
  unsigned shift = 0;
  size_t i = 0;
  while (1) {
    if (i >= len || shift > 63) return 0;
    uint8_t byte = buf[i++];
    n |= (uint64_t)(byte & 0x7F) << shift;
    if (!(byte & 0x80)) {
      *out = n;
      return i;
    }
    shift += 7;
  }
}

static inline uint64_t corro_zigzag(int64_t v) {
  return ((uint64_t)v << 1) ^ (uint64_t)(v >> 63);
}

static inline int64_t corro_unzigzag(uint64_t z) {
  return (int64_t)(z >> 1) ^ -(int64_t)(z & 1);
}

/* ---- big-endian doubles -------------------------------------------------- */

static inline void corro_write_be_double(corro_buf *b, double d) {
  uint64_t bits;
  memcpy(&bits, &d, 8);
  uint8_t be[8];
  for (int i = 0; i < 8; i++) be[i] = (uint8_t)(bits >> (56 - 8 * i));
  corro_buf_put(b, be, 8);
}

static inline double corro_read_be_double(const uint8_t *p) {
  uint64_t bits = 0;
  for (int i = 0; i < 8; i++) bits = (bits << 8) | p[i];
  double d;
  memcpy(&d, &bits, 8);
  return d;
}

/* ---- packed-column iteration -------------------------------------------- */

typedef struct {
  uint8_t tag;
  int64_t i;          /* CORRO_T_INT */
  double r;           /* CORRO_T_REAL */
  const uint8_t *ptr; /* CORRO_T_TEXT / CORRO_T_BLOB payload */
  size_t len;
} corro_col;

/* Parse the next packed column at buf[*off]; advances *off.
 * Returns 1 on success, 0 at end of blob, -1 on malformed data. */
static inline int corro_next_col(const uint8_t *buf, size_t len, size_t *off,
                                 corro_col *out) {
  if (*off >= len) return 0;
  uint8_t tag = buf[(*off)++];
  out->tag = tag;
  switch (tag) {
    case CORRO_T_NULL:
      return 1;
    case CORRO_T_INT: {
      uint64_t z;
      size_t n = corro_read_varint(buf + *off, len - *off, &z);
      if (!n) return -1;
      *off += n;
      out->i = corro_unzigzag(z);
      return 1;
    }
    case CORRO_T_REAL: {
      if (*off + 8 > len) return -1;
      out->r = corro_read_be_double(buf + *off);
      *off += 8;
      return 1;
    }
    case CORRO_T_TEXT:
    case CORRO_T_BLOB: {
      uint64_t n;
      size_t used = corro_read_varint(buf + *off, len - *off, &n);
      if (!used) return -1;
      *off += used;
      if (n > len - *off) return -1;
      out->ptr = buf + *off;
      out->len = (size_t)n;
      *off += (size_t)n;
      return 1;
    }
    default:
      return -1;
  }
}

/* Number of columns in a packed blob, or -1 if malformed. */
static inline int corro_col_count(const uint8_t *buf, size_t len) {
  size_t off = 0;
  corro_col c;
  int count = 0;
  int rc;
  while ((rc = corro_next_col(buf, len, &off, &c)) == 1) count++;
  return rc < 0 ? -1 : count;
}

/* ---- exact SQLite cross-type value comparison --------------------------- */

/* Exact i64-vs-double comparison (no precision loss for |i| > 2^53),
 * the same approach as SQLite's sqlite3IntFloatCompare. */
static inline int corro_int_float_cmp(int64_t i, double r) {
  if (r != r) return 1; /* NaN sorts below every numeric */
  if (r < -9223372036854775808.0) return 1;
  if (r >= 9223372036854775808.0) return -1;
  int64_t y = (int64_t)r;
  if (i < y) return -1;
  if (i > y) return 1;
  double s = (double)i;
  if (s < r) return -1;
  if (s > r) return 1;
  return 0;
}

static inline int corro_mem_cmp(const uint8_t *a, size_t an, const uint8_t *b,
                                size_t bn) {
  size_t n = an < bn ? an : bn;
  int c = n ? memcmp(a, b, n) : 0;
  if (c) return c < 0 ? -1 : 1;
  if (an == bn) return 0;
  return an < bn ? -1 : 1;
}

/* Compare two parsed columns with SQLite semantics: NULL < numeric <
 * text < blob; ints and reals share the numeric class. UTF-8 memcmp order
 * equals code-point order, matching Python str comparison. */
static inline int corro_value_cmp(const corro_col *a, const corro_col *b) {
  int ca = a->tag == CORRO_T_REAL ? CORRO_T_INT : a->tag;
  int cb = b->tag == CORRO_T_REAL ? CORRO_T_INT : b->tag;
  if (ca != cb) return ca < cb ? -1 : 1;
  switch (ca) {
    case CORRO_T_NULL:
      return 0;
    case CORRO_T_INT: {
      if (a->tag == CORRO_T_INT && b->tag == CORRO_T_INT)
        return a->i < b->i ? -1 : a->i > b->i ? 1 : 0;
      if (a->tag == CORRO_T_INT) return corro_int_float_cmp(a->i, b->r);
      if (b->tag == CORRO_T_INT) return -corro_int_float_cmp(b->i, a->r);
      if (a->r != a->r) return b->r != b->r ? 0 : -1; /* NaN lowest */
      if (b->r != b->r) return 1;
      return a->r < b->r ? -1 : a->r > b->r ? 1 : 0;
    }
    default:
      return corro_mem_cmp(a->ptr, a->len, b->ptr, b->len);
  }
}

#endif /* CORRO_CORE_H */
