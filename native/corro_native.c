/* corrosion_tpu._corro_native — CPython extension: the native data-path
 * runtime for the host agent.
 *
 * Provides (with pure-Python fallbacks in corrosion_tpu/core/values.py and
 * corrosion_tpu/agent/transport.py):
 *
 *   pack_columns(seq)   -> bytes   packed-PK codec (values.py:71-95)
 *   unpack_columns(b)   -> tuple   inverse, with malformed-blob rejection
 *   value_cmp(a, b)     -> int     exact SQLite cross-type value ordering
 *                                  (LWW tie-break, doc/crdts.md:15-16)
 *   encode(obj)         -> bytes   compact binary wire codec for frame
 *   decode(b)           -> obj     payloads — the speedy-encoding analogue
 *                                  (corro-types/src/broadcast.rs UniPayload
 *                                  derives speedy Readable/Writable); the
 *                                  JSON+hex frame codec remains the
 *                                  interoperable fallback
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include "corro_core.h"

/* ---- wire codec tags (generic value encoding) --------------------------- */
enum {
  W_NULL = 0,
  W_FALSE = 1,
  W_TRUE = 2,
  W_INT = 3,
  W_FLOAT = 4,
  W_STR = 5,
  W_BYTES = 6,
  W_LIST = 7,
  W_DICT = 8,
};

#define MAX_DEPTH 64

static PyObject *CorroError; /* maps to ValueError subclass-ish usage */

/* ---- pack_columns ------------------------------------------------------- */

static int pack_one(corro_buf *b, PyObject *v) {
  if (v == Py_None) {
    corro_buf_put_u8(b, CORRO_T_NULL);
    return 0;
  }
  if (PyBool_Check(v)) {
    corro_buf_put_u8(b, CORRO_T_INT);
    corro_write_varint(b, corro_zigzag(v == Py_True ? 1 : 0));
    return 0;
  }
  if (PyLong_Check(v)) {
    int overflow = 0;
    long long n = PyLong_AsLongLongAndOverflow(v, &overflow);
    if (overflow || (n == -1 && PyErr_Occurred())) {
      PyErr_Clear();
      PyErr_SetString(PyExc_ValueError, "integer out of SQLite i64 range");
      return -1;
    }
    corro_buf_put_u8(b, CORRO_T_INT);
    corro_write_varint(b, corro_zigzag((int64_t)n));
    return 0;
  }
  if (PyFloat_Check(v)) {
    corro_buf_put_u8(b, CORRO_T_REAL);
    corro_write_be_double(b, PyFloat_AS_DOUBLE(v));
    return 0;
  }
  if (PyUnicode_Check(v)) {
    Py_ssize_t n;
    const char *s = PyUnicode_AsUTF8AndSize(v, &n);
    if (!s) return -1;
    corro_buf_put_u8(b, CORRO_T_TEXT);
    corro_write_varint(b, (uint64_t)n);
    corro_buf_put(b, s, (size_t)n);
    return 0;
  }
  if (PyBytes_Check(v) || PyByteArray_Check(v) || PyMemoryView_Check(v)) {
    Py_buffer view;
    if (PyObject_GetBuffer(v, &view, PyBUF_SIMPLE)) return -1;
    corro_buf_put_u8(b, CORRO_T_BLOB);
    corro_write_varint(b, (uint64_t)view.len);
    corro_buf_put(b, view.buf, (size_t)view.len);
    PyBuffer_Release(&view);
    return 0;
  }
  PyErr_Format(PyExc_TypeError, "unsupported SQL value type: %.200s",
               Py_TYPE(v)->tp_name);
  return -1;
}

static PyObject *py_pack_columns(PyObject *self, PyObject *arg) {
  (void)self;
  PyObject *it = PyObject_GetIter(arg);
  if (!it) return NULL;
  corro_buf b;
  corro_buf_init(&b);
  PyObject *item;
  while ((item = PyIter_Next(it))) {
    int rc = pack_one(&b, item);
    Py_DECREF(item);
    if (rc) {
      Py_DECREF(it);
      corro_buf_free(&b);
      return NULL;
    }
  }
  Py_DECREF(it);
  if (PyErr_Occurred() || b.oom) {
    corro_buf_free(&b);
    return b.oom ? PyErr_NoMemory() : NULL;
  }
  PyObject *out = PyBytes_FromStringAndSize((const char *)b.data,
                                            (Py_ssize_t)b.len);
  corro_buf_free(&b);
  return out;
}

/* ---- unpack_columns ----------------------------------------------------- */

static PyObject *col_to_py(const corro_col *c) {
  switch (c->tag) {
    case CORRO_T_NULL:
      Py_RETURN_NONE;
    case CORRO_T_INT:
      return PyLong_FromLongLong((long long)c->i);
    case CORRO_T_REAL:
      return PyFloat_FromDouble(c->r);
    case CORRO_T_TEXT:
      return PyUnicode_DecodeUTF8((const char *)c->ptr, (Py_ssize_t)c->len,
                                  NULL);
    default:
      return PyBytes_FromStringAndSize((const char *)c->ptr,
                                       (Py_ssize_t)c->len);
  }
}

static PyObject *py_unpack_columns(PyObject *self, PyObject *arg) {
  (void)self;
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE)) return NULL;
  const uint8_t *buf = (const uint8_t *)view.buf;
  size_t len = (size_t)view.len;
  PyObject *list = PyList_New(0);
  if (!list) {
    PyBuffer_Release(&view);
    return NULL;
  }
  size_t off = 0;
  corro_col c;
  int rc;
  while ((rc = corro_next_col(buf, len, &off, &c)) == 1) {
    PyObject *v = col_to_py(&c);
    if (!v || PyList_Append(list, v)) {
      Py_XDECREF(v);
      goto fail;
    }
    Py_DECREF(v);
  }
  if (rc < 0) {
    PyErr_SetObject(CorroError,
                    PyUnicode_FromFormat("malformed packed blob at offset %zu",
                                         off));
    goto fail;
  }
  PyBuffer_Release(&view);
  PyObject *tup = PyList_AsTuple(list);
  Py_DECREF(list);
  return tup;
fail:
  PyBuffer_Release(&view);
  Py_DECREF(list);
  return NULL;
}

/* ---- value_cmp ---------------------------------------------------------- */

/* Parse a Python SqliteValue into a corro_col; borrowed buffers stay alive
 * while the caller holds the value. Returns 0 ok / -1 error. */
static int py_to_col(PyObject *v, corro_col *c, Py_buffer *view,
                     int *has_view) {
  *has_view = 0;
  if (v == Py_None) {
    c->tag = CORRO_T_NULL;
    return 0;
  }
  if (PyBool_Check(v)) {
    c->tag = CORRO_T_INT;
    c->i = v == Py_True;
    return 0;
  }
  if (PyLong_Check(v)) {
    int overflow = 0;
    long long n = PyLong_AsLongLongAndOverflow(v, &overflow);
    if (overflow || (n == -1 && PyErr_Occurred())) {
      PyErr_Clear();
      PyErr_SetString(PyExc_OverflowError, "integer out of i64 range");
      return -1;
    }
    c->tag = CORRO_T_INT;
    c->i = (int64_t)n;
    return 0;
  }
  if (PyFloat_Check(v)) {
    c->tag = CORRO_T_REAL;
    c->r = PyFloat_AS_DOUBLE(v);
    return 0;
  }
  if (PyUnicode_Check(v)) {
    Py_ssize_t n;
    const char *s = PyUnicode_AsUTF8AndSize(v, &n);
    if (!s) return -1;
    c->tag = CORRO_T_TEXT;
    c->ptr = (const uint8_t *)s;
    c->len = (size_t)n;
    return 0;
  }
  if (PyObject_CheckBuffer(v)) {
    if (PyObject_GetBuffer(v, view, PyBUF_SIMPLE)) return -1;
    *has_view = 1;
    c->tag = CORRO_T_BLOB;
    c->ptr = (const uint8_t *)view->buf;
    c->len = (size_t)view->len;
    return 0;
  }
  PyErr_Format(PyExc_TypeError, "unsupported SQL value type: %.200s",
               Py_TYPE(v)->tp_name);
  return -1;
}

static PyObject *py_value_cmp(PyObject *self, PyObject *args) {
  (void)self;
  PyObject *a, *b;
  if (!PyArg_ParseTuple(args, "OO", &a, &b)) return NULL;
  corro_col ca, cb;
  Py_buffer va, vb;
  int ha = 0, hb = 0;
  int rc = py_to_col(a, &ca, &va, &ha);
  if (!rc) rc = py_to_col(b, &cb, &vb, &hb);
  PyObject *out = NULL;
  if (!rc) out = PyLong_FromLong(corro_value_cmp(&ca, &cb));
  if (ha) PyBuffer_Release(&va);
  if (hb) PyBuffer_Release(&vb);
  return out;
}

/* ---- generic wire codec (speedy analogue) ------------------------------- */

static int encode_obj(corro_buf *b, PyObject *v, int depth) {
  if (depth > MAX_DEPTH) {
    PyErr_SetString(PyExc_ValueError, "wire value nested too deeply");
    return -1;
  }
  if (v == Py_None) {
    corro_buf_put_u8(b, W_NULL);
    return 0;
  }
  if (PyBool_Check(v)) {
    corro_buf_put_u8(b, v == Py_True ? W_TRUE : W_FALSE);
    return 0;
  }
  if (PyLong_Check(v)) {
    int overflow = 0;
    long long n = PyLong_AsLongLongAndOverflow(v, &overflow);
    if (overflow || (n == -1 && PyErr_Occurred())) {
      PyErr_Clear();
      PyErr_SetString(PyExc_ValueError, "wire integer out of i64 range");
      return -1;
    }
    corro_buf_put_u8(b, W_INT);
    corro_write_varint(b, corro_zigzag((int64_t)n));
    return 0;
  }
  if (PyFloat_Check(v)) {
    corro_buf_put_u8(b, W_FLOAT);
    corro_write_be_double(b, PyFloat_AS_DOUBLE(v));
    return 0;
  }
  if (PyUnicode_Check(v)) {
    Py_ssize_t n;
    const char *s = PyUnicode_AsUTF8AndSize(v, &n);
    if (!s) return -1;
    corro_buf_put_u8(b, W_STR);
    corro_write_varint(b, (uint64_t)n);
    corro_buf_put(b, s, (size_t)n);
    return 0;
  }
  if (PyBytes_Check(v) || PyByteArray_Check(v) || PyMemoryView_Check(v)) {
    Py_buffer view;
    if (PyObject_GetBuffer(v, &view, PyBUF_SIMPLE)) return -1;
    corro_buf_put_u8(b, W_BYTES);
    corro_write_varint(b, (uint64_t)view.len);
    corro_buf_put(b, view.buf, (size_t)view.len);
    PyBuffer_Release(&view);
    return 0;
  }
  if (PyList_Check(v) || PyTuple_Check(v)) {
    Py_ssize_t n = PySequence_Fast_GET_SIZE(v);
    corro_buf_put_u8(b, W_LIST);
    corro_write_varint(b, (uint64_t)n);
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject *item = PyList_Check(v) ? PyList_GET_ITEM(v, i)
                                       : PyTuple_GET_ITEM(v, i);
      if (encode_obj(b, item, depth + 1)) return -1;
    }
    return 0;
  }
  if (PyDict_Check(v)) {
    corro_buf_put_u8(b, W_DICT);
    corro_write_varint(b, (uint64_t)PyDict_Size(v));
    Py_ssize_t pos = 0;
    PyObject *key, *val;
    while (PyDict_Next(v, &pos, &key, &val)) {
      if (!PyUnicode_Check(key)) {
        PyErr_SetString(PyExc_TypeError, "wire dict keys must be str");
        return -1;
      }
      Py_ssize_t n;
      const char *s = PyUnicode_AsUTF8AndSize(key, &n);
      if (!s) return -1;
      corro_write_varint(b, (uint64_t)n);
      corro_buf_put(b, s, (size_t)n);
      if (encode_obj(b, val, depth + 1)) return -1;
    }
    return 0;
  }
  PyErr_Format(PyExc_TypeError, "unsupported wire value type: %.200s",
               Py_TYPE(v)->tp_name);
  return -1;
}

static PyObject *py_encode(PyObject *self, PyObject *arg) {
  (void)self;
  corro_buf b;
  corro_buf_init(&b);
  if (encode_obj(&b, arg, 0)) {
    corro_buf_free(&b);
    return NULL;
  }
  if (b.oom) {
    corro_buf_free(&b);
    return PyErr_NoMemory();
  }
  PyObject *out = PyBytes_FromStringAndSize((const char *)b.data,
                                            (Py_ssize_t)b.len);
  corro_buf_free(&b);
  return out;
}

static PyObject *decode_obj(const uint8_t *buf, size_t len, size_t *off,
                            int depth) {
  if (depth > MAX_DEPTH) {
    PyErr_SetString(CorroError, "wire value nested too deeply");
    return NULL;
  }
  if (*off >= len) {
    PyErr_SetString(CorroError, "truncated wire value");
    return NULL;
  }
  uint8_t tag = buf[(*off)++];
  switch (tag) {
    case W_NULL:
      Py_RETURN_NONE;
    case W_FALSE:
      Py_RETURN_FALSE;
    case W_TRUE:
      Py_RETURN_TRUE;
    case W_INT: {
      uint64_t z;
      size_t n = corro_read_varint(buf + *off, len - *off, &z);
      if (!n) goto truncated;
      *off += n;
      return PyLong_FromLongLong((long long)corro_unzigzag(z));
    }
    case W_FLOAT: {
      if (*off + 8 > len) goto truncated;
      double d = corro_read_be_double(buf + *off);
      *off += 8;
      return PyFloat_FromDouble(d);
    }
    case W_STR:
    case W_BYTES: {
      uint64_t n;
      size_t used = corro_read_varint(buf + *off, len - *off, &n);
      if (!used || n > len - *off - used) goto truncated;
      *off += used;
      const char *p = (const char *)(buf + *off);
      *off += (size_t)n;
      return tag == W_STR
                 ? PyUnicode_DecodeUTF8(p, (Py_ssize_t)n, NULL)
                 : PyBytes_FromStringAndSize(p, (Py_ssize_t)n);
    }
    case W_LIST: {
      uint64_t n;
      size_t used = corro_read_varint(buf + *off, len - *off, &n);
      if (!used || n > len - *off) goto truncated; /* ≥1 byte per item */
      *off += used;
      PyObject *list = PyList_New((Py_ssize_t)n);
      if (!list) return NULL;
      for (uint64_t i = 0; i < n; i++) {
        PyObject *item = decode_obj(buf, len, off, depth + 1);
        if (!item) {
          Py_DECREF(list);
          return NULL;
        }
        PyList_SET_ITEM(list, (Py_ssize_t)i, item);
      }
      return list;
    }
    case W_DICT: {
      uint64_t n;
      size_t used = corro_read_varint(buf + *off, len - *off, &n);
      if (!used || n > len - *off) goto truncated;
      *off += used;
      PyObject *dict = PyDict_New();
      if (!dict) return NULL;
      for (uint64_t i = 0; i < n; i++) {
        uint64_t kn;
        size_t ku = corro_read_varint(buf + *off, len - *off, &kn);
        if (!ku || kn > len - *off - ku) {
          Py_DECREF(dict);
          goto truncated;
        }
        *off += ku;
        PyObject *key = PyUnicode_DecodeUTF8((const char *)(buf + *off),
                                             (Py_ssize_t)kn, NULL);
        *off += (size_t)kn;
        if (!key) {
          Py_DECREF(dict);
          return NULL;
        }
        PyObject *val = decode_obj(buf, len, off, depth + 1);
        if (!val || PyDict_SetItem(dict, key, val)) {
          Py_DECREF(key);
          Py_XDECREF(val);
          Py_DECREF(dict);
          return NULL;
        }
        Py_DECREF(key);
        Py_DECREF(val);
      }
      return dict;
    }
    default:
      PyErr_Format(CorroError, "bad wire tag %d at offset %zu", tag,
                   *off - 1);
      return NULL;
  }
truncated:
  PyErr_SetString(CorroError, "truncated wire value");
  return NULL;
}

static PyObject *py_decode(PyObject *self, PyObject *arg) {
  (void)self;
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE)) return NULL;
  size_t off = 0;
  PyObject *out = decode_obj((const uint8_t *)view.buf, (size_t)view.len,
                             &off, 0);
  if (out && off != (size_t)view.len) {
    Py_DECREF(out);
    out = NULL;
    PyErr_SetString(CorroError, "trailing bytes after wire value");
  }
  PyBuffer_Release(&view);
  return out;
}

/* ---- module ------------------------------------------------------------- */

static PyMethodDef methods[] = {
    {"pack_columns", py_pack_columns, METH_O,
     "Serialize a sequence of SQL values into one packed-PK blob."},
    {"unpack_columns", py_unpack_columns, METH_O,
     "Parse a packed-PK blob back into a tuple of SQL values."},
    {"value_cmp", py_value_cmp, METH_VARARGS,
     "Exact SQLite cross-type comparison of two SQL values (-1/0/1)."},
    {"encode", py_encode, METH_O,
     "Encode a JSON-able value (+ bytes) into the compact binary wire form."},
    {"decode", py_decode, METH_O, "Decode the compact binary wire form."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_corro_native",
    "Native data-path runtime (codec + value ordering) for corrosion_tpu.",
    -1, methods, NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC PyInit__corro_native(void) {
  PyObject *m = PyModule_Create(&moduledef);
  if (!m) return NULL;
  CorroError = PyErr_NewException("_corro_native.MalformedError",
                                  PyExc_ValueError, NULL);
  if (!CorroError || PyModule_AddObject(m, "MalformedError", CorroError)) {
    Py_XDECREF(CorroError);
    Py_DECREF(m);
    return NULL;
  }
  return m;
}
