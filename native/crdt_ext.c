/* crdt_ext — SQLite run-time loadable extension: the native CRDT helper
 * functions the host Store loads into every connection.
 *
 * This is the architectural analogue of the reference's vendored cr-sqlite
 * C extension (corro-types/src/sqlite.rs:20-26,87-105 loads prebuilt
 * sqlite3_crsqlite_init blobs into every conn). Our CRR layer keeps the
 * clock/causal-length tables in plain SQL (agent/store.py), and this
 * extension supplies the pieces SQL cannot express natively:
 *
 *   crdt_value_cmp(a, b)      -> -1/0/1  exact SQLite cross-type ordering,
 *                                        the LWW "biggest value wins"
 *                                        tie-break (doc/crdts.md:15-16).
 *                                        With it, a remote cell merge is a
 *                                        single conditional UPDATE instead
 *                                        of SELECT + host compare + UPDATE.
 *   crdt_pack_columns(v...)   -> blob    packed-PK codec (values.py:71-95)
 *   crdt_unpack_col(blob, i)  -> value   i-th packed column (0-based)
 *   crdt_col_count(blob)      -> int     column count / malformed check
 *   crdt_site_hex(blob)       -> text    site-id rendering for diagnostics
 *
 * All functions are deterministic, so SQLite may use them in indexes and
 * partial-index predicates.
 */
#include "sqlite3ext.h"
SQLITE_EXTENSION_INIT1

#include "corro_core.h"

/* Parse an sqlite3_value into a corro_col (no copies; SQLite owns memory
 * for the duration of the function call). */
static int sqval_to_col(sqlite3_value *v, corro_col *c) {
  switch (sqlite3_value_type(v)) {
    case SQLITE_NULL:
      c->tag = CORRO_T_NULL;
      return 0;
    case SQLITE_INTEGER:
      c->tag = CORRO_T_INT;
      c->i = sqlite3_value_int64(v);
      return 0;
    case SQLITE_FLOAT:
      c->tag = CORRO_T_REAL;
      c->r = sqlite3_value_double(v);
      return 0;
    case SQLITE_TEXT:
      c->tag = CORRO_T_TEXT;
      c->ptr = (const uint8_t *)sqlite3_value_text(v);
      c->len = (size_t)sqlite3_value_bytes(v);
      return 0;
    case SQLITE_BLOB:
      c->tag = CORRO_T_BLOB;
      c->ptr = (const uint8_t *)sqlite3_value_blob(v);
      c->len = (size_t)sqlite3_value_bytes(v);
      return 0;
    default:
      return -1;
  }
}

static void fn_value_cmp(sqlite3_context *ctx, int argc,
                         sqlite3_value **argv) {
  corro_col a, b;
  if (argc != 2 || sqval_to_col(argv[0], &a) || sqval_to_col(argv[1], &b)) {
    sqlite3_result_error(ctx, "crdt_value_cmp expects two SQL values", -1);
    return;
  }
  sqlite3_result_int(ctx, corro_value_cmp(&a, &b));
}

static void fn_pack_columns(sqlite3_context *ctx, int argc,
                            sqlite3_value **argv) {
  corro_buf buf;
  corro_buf_init(&buf);
  for (int i = 0; i < argc; i++) {
    corro_col c;
    if (sqval_to_col(argv[i], &c)) {
      corro_buf_free(&buf);
      sqlite3_result_error(ctx, "crdt_pack_columns: unsupported value", -1);
      return;
    }
    corro_buf_put_u8(&buf, c.tag);
    switch (c.tag) {
      case CORRO_T_NULL:
        break;
      case CORRO_T_INT:
        corro_write_varint(&buf, corro_zigzag(c.i));
        break;
      case CORRO_T_REAL:
        corro_write_be_double(&buf, c.r);
        break;
      default:
        corro_write_varint(&buf, (uint64_t)c.len);
        corro_buf_put(&buf, c.ptr, c.len);
    }
  }
  if (buf.oom) {
    corro_buf_free(&buf);
    sqlite3_result_error_nomem(ctx);
    return;
  }
  sqlite3_result_blob(ctx, buf.data, (int)buf.len, SQLITE_TRANSIENT);
  corro_buf_free(&buf);
}

static void col_to_result(sqlite3_context *ctx, const corro_col *c) {
  switch (c->tag) {
    case CORRO_T_NULL:
      sqlite3_result_null(ctx);
      return;
    case CORRO_T_INT:
      sqlite3_result_int64(ctx, c->i);
      return;
    case CORRO_T_REAL:
      sqlite3_result_double(ctx, c->r);
      return;
    case CORRO_T_TEXT:
      sqlite3_result_text(ctx, (const char *)c->ptr, (int)c->len,
                          SQLITE_TRANSIENT);
      return;
    default:
      sqlite3_result_blob(ctx, c->ptr, (int)c->len, SQLITE_TRANSIENT);
  }
}

static void fn_unpack_col(sqlite3_context *ctx, int argc,
                          sqlite3_value **argv) {
  if (argc != 2 || sqlite3_value_type(argv[0]) != SQLITE_BLOB) {
    sqlite3_result_error(ctx, "crdt_unpack_col(blob, index)", -1);
    return;
  }
  const uint8_t *buf = (const uint8_t *)sqlite3_value_blob(argv[0]);
  size_t len = (size_t)sqlite3_value_bytes(argv[0]);
  sqlite3_int64 want = sqlite3_value_int64(argv[1]);
  size_t off = 0;
  corro_col c;
  sqlite3_int64 idx = 0;
  int rc;
  while ((rc = corro_next_col(buf, len, &off, &c)) == 1) {
    if (idx++ == want) {
      col_to_result(ctx, &c);
      return;
    }
  }
  if (rc < 0)
    sqlite3_result_error(ctx, "crdt_unpack_col: malformed blob", -1);
  else
    sqlite3_result_null(ctx); /* index out of range */
}

static void fn_col_count(sqlite3_context *ctx, int argc,
                         sqlite3_value **argv) {
  if (argc != 1 || sqlite3_value_type(argv[0]) != SQLITE_BLOB) {
    sqlite3_result_error(ctx, "crdt_col_count(blob)", -1);
    return;
  }
  int n = corro_col_count((const uint8_t *)sqlite3_value_blob(argv[0]),
                          (size_t)sqlite3_value_bytes(argv[0]));
  if (n < 0)
    sqlite3_result_error(ctx, "crdt_col_count: malformed blob", -1);
  else
    sqlite3_result_int(ctx, n);
}

static void fn_site_hex(sqlite3_context *ctx, int argc, sqlite3_value **argv) {
  static const char hexd[] = "0123456789abcdef";
  if (argc != 1 || sqlite3_value_type(argv[0]) != SQLITE_BLOB) {
    sqlite3_result_error(ctx, "crdt_site_hex(blob)", -1);
    return;
  }
  const uint8_t *p = (const uint8_t *)sqlite3_value_blob(argv[0]);
  int n = sqlite3_value_bytes(argv[0]);
  char *out = (char *)sqlite3_malloc(2 * n + 1);
  if (!out) {
    sqlite3_result_error_nomem(ctx);
    return;
  }
  for (int i = 0; i < n; i++) {
    out[2 * i] = hexd[p[i] >> 4];
    out[2 * i + 1] = hexd[p[i] & 0xF];
  }
  out[2 * n] = 0;
  sqlite3_result_text(ctx, out, 2 * n, sqlite3_free);
}

#ifdef _WIN32
__declspec(dllexport)
#endif
int sqlite3_crdtext_init(sqlite3 *db, char **pzErrMsg,
                         const sqlite3_api_routines *pApi) {
  (void)pzErrMsg;
  SQLITE_EXTENSION_INIT2(pApi);
  const int flags = SQLITE_UTF8 | SQLITE_DETERMINISTIC;
  int rc = sqlite3_create_function(db, "crdt_value_cmp", 2, flags, 0,
                                   fn_value_cmp, 0, 0);
  if (rc == SQLITE_OK)
    rc = sqlite3_create_function(db, "crdt_pack_columns", -1, flags, 0,
                                 fn_pack_columns, 0, 0);
  if (rc == SQLITE_OK)
    rc = sqlite3_create_function(db, "crdt_unpack_col", 2, flags, 0,
                                 fn_unpack_col, 0, 0);
  if (rc == SQLITE_OK)
    rc = sqlite3_create_function(db, "crdt_col_count", 1, flags, 0,
                                 fn_col_count, 0, 0);
  if (rc == SQLITE_OK)
    rc = sqlite3_create_function(db, "crdt_site_hex", 1, flags, 0,
                                 fn_site_hex, 0, 0);
  return rc;
}
